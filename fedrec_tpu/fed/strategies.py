"""Federated aggregation strategies — one interface, three reference modes.

The reference implements federation three times with copy-paste drivers
(SURVEY.md section 1): DDP gradient sync (``Gradient_Averaging_main.py:119,146``),
explicit per-epoch parameter allreduce (``Parameter_Averaging_main.py:144-148``),
and a hub-and-spoke server that broadcasts weights and gathers full
state_dicts over TCP (``server.py:72-103``, ``client.py:256-291``). Here each
mode is a small strategy object whose hooks are called *inside* the jitted
SPMD train step, so the federation collectives compile into the same XLA
program as the model math and ride ICI:

  * ``GradAvg``  — ``sync_grads`` = ``lax.pmean`` each step (DDP parity)
  * ``ParamAvg`` — ``sync_params`` = ``lax.pmean`` at round end (FedAvg with
    equal weights, exactly ``all_reduce(param)/world_size``)
  * ``Local``    — no cross-client communication (single-client / debugging)

The coordinator deployment (server process + client processes) reuses
``weighted_param_avg``: per-round participation masks generalize the
equal-weight mean to client subsets, fixing the reference's "one client dies
=> whole training dies" limitation (Final_Report.pdf section VII.a; see
SURVEY.md section 5.3).
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
from jax import lax


class FedStrategy:
    """Hooks called inside the jitted step / round sync. Default: no comms.

    ``sync_grads_every_step`` / ``sync_params_every_round`` are read by the
    Trainer to decide which collectives to schedule; ``sync_grads`` runs
    inside the per-batch step, ``sync_params`` inside the round-end sync
    (``fedrec_tpu.train.step.build_param_sync``).
    """

    name = "local"
    sync_grads_every_step = False
    sync_params_every_round = False

    def sync_grads(self, grads: Any, axis: str) -> Any:
        return grads

    def sync_params(self, params: Any, weight: jnp.ndarray, axis: str) -> Any:
        return params


class Local(FedStrategy):
    pass


class GradAvg(FedStrategy):
    """Per-step gradient averaging (DDP-parity: reference
    ``Gradient_Averaging_main.py:119`` — sync happens inside backward)."""

    name = "grad_avg"
    sync_grads_every_step = True

    def sync_grads(self, grads: Any, axis: str) -> Any:
        return lax.pmean(grads, axis_name=axis)


class ParamAvg(FedStrategy):
    """Per-round parameter averaging (FedAvg): reference
    ``Parameter_Averaging_main.py:144-148`` — ``all_reduce(SUM)/world_size``.
    Participation-weighted: equal weights reproduce the reference exactly."""

    name = "param_avg"
    sync_params_every_round = True

    def sync_params(self, params: Any, weight: jnp.ndarray, axis: str) -> Any:
        return weighted_param_avg(params, weight, axis)


_STRATEGIES = {s.name: s for s in (Local, GradAvg, ParamAvg)}


def get_strategy(name: str) -> FedStrategy:
    # "coordinator" shares the device-side math with param_avg; its host-side
    # round loop lives in fedrec_tpu.fed.coordinator
    key = "param_avg" if name == "coordinator" else name
    if key not in _STRATEGIES:
        raise ValueError(f"unknown federation strategy {name!r}; have {sorted(_STRATEGIES)}")
    return _STRATEGIES[key]()


def participation_mask(
    rng: jax.Array, num_clients: int, fraction: float
) -> jnp.ndarray:
    """(num_clients,) float mask with at least one participant per round.

    Client dropout tolerance: rounds aggregate over the subset that reported
    (the reference instead dies if any client fails — Final_Report.pdf
    section VII.a).
    """
    if fraction >= 1.0:
        return jnp.ones((num_clients,), dtype=jnp.float32)
    scores = jax.random.uniform(rng, (num_clients,))
    k = max(1, int(round(fraction * num_clients)))
    threshold = jnp.sort(scores)[k - 1]
    return (scores <= threshold).astype(jnp.float32)


def weighted_param_avg(params: Any, weight: jnp.ndarray, axis: str) -> Any:
    """Participation-weighted FedAvg inside ``shard_map``.

    ``weight`` is this client's scalar round weight (0 = dropped out).
    Every client — including non-participants — adopts the aggregate,
    mirroring the coordinator broadcast (reference ``server.py:76-77``).
    A round where NO client reports keeps everyone's local parameters
    (rather than dividing by zero into NaN).

    Zero-weight contributions are masked out of the sum, not multiplied
    in: a quarantined/faulted client whose parameters are NaN must
    contribute nothing — ``NaN * 0`` would still be NaN and poison every
    participant (``fedrec_tpu.fed.robust``). For finite params this is
    bit-identical to the plain ``psum(p * w)``.
    """
    total = lax.psum(weight, axis_name=axis)
    safe_total = jnp.where(total > 0, total, 1.0)
    return jax.tree_util.tree_map(
        lambda p: jnp.where(
            total > 0,
            lax.psum(jnp.where(weight > 0, p * weight, 0.0), axis_name=axis)
            / safe_total,
            p,
        ),
        params,
    )


class ServerOptimizer:
    """Server-side optimization over round deltas (FedOpt family).

    Plain FedAvg (the reference's only aggregation,
    ``Parameter_Averaging_main.py:144-148``) ADOPTS the client mean each
    round. The FedOpt view (Reddi et al. 2021 "Adaptive Federated
    Optimization") instead treats ``global - mean`` as a pseudo-gradient and
    feeds it to a server optimizer, giving momentum/adaptivity across
    rounds without touching client code:

        delta  = global - mean            # pseudo-gradient
        global = global + server_opt(delta)

    ``kind='sgd'`` with ``lr=1, momentum=0`` reproduces FedAvg exactly;
    ``momentum>0`` is FedAvgM; ``kind='adam'`` is FedAdam. State (momentum /
    adaptivity buffers) lives host-side on the SERVER ONLY: in the
    coordinator deployment clients adopt the plain mean and receive the
    server's post-opt global at the next round's fan-out, so client hosts
    never hold (and cannot desync) optimizer state.

    Pure numpy by design: the server step is a tiny host-side round-boundary
    computation (~2M params), and keeping it off the devices means zero extra
    device programs racing the round's collectives (on single-core XLA:CPU
    rigs that race can starve the 8-way rendezvous into its termination
    deadline; on TPU it is simply wasted dispatch).
    """

    def __init__(self, kind: str = "sgd", lr: float = 1.0, momentum: float = 0.0):
        if kind not in ("sgd", "adam"):
            raise ValueError(f"unknown server optimizer {kind!r}; 'sgd' | 'adam'")
        self.kind, self.lr, self.momentum = kind, float(lr), float(momentum)
        self.b1, self.b2, self.eps = 0.9, 0.999, 1e-8  # optax.adam defaults
        self._state: dict | None = None

    def _tmap(self, fn, *trees):
        import numpy as onp

        return jax.tree_util.tree_map(
            lambda *xs: fn(*[onp.asarray(x) for x in xs]), *trees
        )

    def _init_state(self, params: Any) -> dict:
        import numpy as onp

        zeros = self._tmap(lambda p: onp.zeros_like(p), params)
        if self.kind == "sgd":
            return {"buf": zeros, "t": 0}
        return {"m": zeros, "v": self._tmap(lambda p: onp.zeros_like(p), params), "t": 0}

    def step(self, global_params: Any, mean_params: Any) -> Any:
        """One server update on host arrays: returns the new global params.

        ``mean_params`` is the round's aggregation PROPOSAL — whatever
        the active reducer produced: the flat weighted mean, a
        hierarchical per-tier robust reduce (``agg.mode=hierarchical``),
        or a staleness-weighted buffered commit (``agg.mode=async``,
        :func:`fedrec_tpu.agg.commit.fold_commit` applied to
        ``global_params``). The FedOpt contract is aggregation-agnostic
        by construction: the pseudo-gradient is always
        ``global - proposal`` against the SAME ``global_params`` the
        proposal was built from, so server momentum/adaptivity state
        sees identical update semantics in every agg mode — a
        zero-staleness all-reporting async commit yields bit-the-same
        pseudo-gradient as the flat mean."""
        import numpy as onp

        delta = self._tmap(lambda g, m: g - m, global_params, mean_params)
        if self._state is None:
            self._state = self._init_state(global_params)
        st = self._state
        st["t"] += 1
        if self.kind == "sgd":
            if self.momentum:
                st["buf"] = self._tmap(
                    lambda b, d: self.momentum * b + d, st["buf"], delta
                )
                upd = st["buf"]
            else:
                upd = delta
            return self._tmap(lambda p, u: p - self.lr * u, global_params, upd)
        # adam (bias-corrected, optax semantics)
        st["m"] = self._tmap(lambda m, d: self.b1 * m + (1 - self.b1) * d, st["m"], delta)
        st["v"] = self._tmap(lambda v, d: self.b2 * v + (1 - self.b2) * d * d, st["v"], delta)
        t = st["t"]
        c1, c2 = 1 - self.b1**t, 1 - self.b2**t
        return self._tmap(
            lambda p, m, v: p - self.lr * (m / c1) / (onp.sqrt(v / c2) + self.eps),
            global_params, st["m"], st["v"],
        )

    # -- persistence: the buffers live host-side, outside the orbax client
    #    snapshot, so resume needs a sidecar for bit-identical FedOpt runs.
    #    The sidecar is round-tagged so a loader can detect state that does
    #    not match the snapshot it resumes from.
    def state_bytes(self, round_idx: int = -1) -> bytes:
        from flax import serialization

        return serialization.to_bytes({"opt": self._state, "round": round_idx})

    def load_state(self, blob: bytes, params_template: Any) -> int:
        """Restore buffers; returns the round the sidecar was written at."""
        from flax import serialization

        if self._state is None:
            self._state = self._init_state(params_template)
        restored = serialization.from_bytes(
            {"opt": self._state, "round": 0}, blob
        )
        self._state = restored["opt"]
        return int(restored["round"])
