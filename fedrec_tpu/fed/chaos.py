"""Deterministic fault injection: a seeded plan of client & host faults.

At production scale client failure is the steady state, not the exception
(FedJAX, arxiv 2108.02117, treats client subsampling/failure as a
first-class simulation primitive) — so the robustness machinery needs a
way to be *exercised*, reproducibly. A :class:`FaultPlan` is a pure
function of ``(chaos config, round index)``: the same plan produces the
same faults on every run and on every rollback replay, so

* a chaos run is bit-identical when re-run (the acceptance bar for the
  chaos smoke), and
* the Trainer's quarantine/rollback replay re-encounters the exact fault
  it rolled back from, proving the quarantine — not luck — saved the
  round.

Client-side faults come in two flavors:

* **participation faults** (``drop``, ``straggle``): the client's round
  weight is forced to 0 — it trains but its contribution is excluded,
  exactly the failure mode the reference dies on
  (Final_Report.pdf VII.a). Stragglers can additionally cost a host-side
  delay (``straggle_ms``) on the host-driven path.
* **update faults** (``nan``, ``scale``, ``flip``): applied as masks at
  the optimizer-update boundary INSIDE the jitted step. The per-client
  ``(code, scale)`` vectors ride the batch dict as ``chaos.code`` /
  ``chaos.scale`` arrays, so every dispatch mode (per-batch, epoch scan,
  rounds-in-jit) compiles the same fault arithmetic, and the flight
  recorder's batch ring captures them — ``fedrec-obs replay`` re-injects
  the fault for free.

Host-level faults (``kill_round``/``kill_process``, guarded by an
on-disk marker so a resumed world doesn't re-die; ``torn_snapshot_round``)
live in the coordinator CLI, which reads the same config section.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

import numpy as np

# update-fault codes carried in the batch's chaos.code vector; 0 = none
FAULT_CODES = {"nan": 1, "scale": 2, "flip": 3}


@dataclass(frozen=True)
class RoundFaults:
    """One round's resolved faults (pure function of plan + round)."""

    weight_mask: np.ndarray            # (C,) float32 0/1 — drop+straggle
    codes: np.ndarray                  # (C,) int32 update-fault codes
    scales: np.ndarray                 # (C,) float32 (code==scale multiplier)
    dropped: tuple = ()
    straggled: tuple = ()
    injected: tuple = ()               # ((kind, client), ...) update faults

    @property
    def any(self) -> bool:
        return bool(
            self.dropped or self.straggled or self.injected
        )


def parse_faults(spec: str, num_clients: int) -> list[tuple[str, int | None, int, float]]:
    """Parse the ``faults`` DSL: comma list of ``kind@round:client[xscale]``
    (``round`` may be ``*`` = every round). Raises on malformed entries so a
    typo'd plan fails at build time, not silently fault-free."""
    out: list[tuple[str, int | None, int, float]] = []
    for item in spec.split(","):
        item = item.strip()
        if not item:
            continue
        try:
            kind, rest = item.split("@", 1)
            round_s, client_s = rest.split(":", 1)
            scale = 1.0
            if "x" in client_s:
                client_s, scale_s = client_s.split("x", 1)
                scale = float(scale_s)
            rnd = None if round_s == "*" else int(round_s)
            client = int(client_s)
        except ValueError:
            raise ValueError(
                f"chaos.faults entry {item!r} is not "
                "'kind@round:client[xscale]' (e.g. 'nan@2:3,scale@*:5x100')"
            ) from None
        if kind not in FAULT_CODES:
            raise ValueError(
                f"chaos.faults entry {item!r}: unknown kind {kind!r}; "
                f"expected one of {sorted(FAULT_CODES)}"
            )
        if not 0 <= client < num_clients:
            raise ValueError(
                f"chaos.faults entry {item!r}: client {client} out of range "
                f"[0, {num_clients})"
            )
        out.append((kind, rnd, client, scale))
    return out


class FaultPlan:
    """Seeded, deterministic per-round fault schedule.

    ``round_faults(r)`` is idempotent: the random drop/straggle draws are
    derived from ``default_rng([seed, r])``, never from mutable state, so
    rollback replays and re-runs see identical faults.
    """

    def __init__(self, chaos_cfg: Any, num_clients: int):
        self.cfg = chaos_cfg
        self.num_clients = int(num_clients)
        self.seed = int(chaos_cfg.seed)
        self.drop_rate = float(chaos_cfg.drop_rate)
        self.straggle_rate = float(chaos_cfg.straggle_rate)
        self.specs = parse_faults(chaos_cfg.faults, self.num_clients)

    def round_faults(self, round_idx: int) -> RoundFaults:
        c = self.num_clients
        mask = np.ones((c,), np.float32)
        dropped: list[int] = []
        straggled: list[int] = []
        if self.drop_rate > 0 or self.straggle_rate > 0:
            rng = np.random.default_rng([self.seed, int(round_idx)])
            u = rng.random(c)
            # one draw decides both: [0, drop) drops, [drop, drop+straggle)
            # straggles — so the rates compose without double-failing
            for i in range(c):
                if u[i] < self.drop_rate:
                    dropped.append(i)
                    mask[i] = 0.0
                elif u[i] < self.drop_rate + self.straggle_rate:
                    straggled.append(i)
                    mask[i] = 0.0
        codes = np.zeros((c,), np.int32)
        scales = np.ones((c,), np.float32)
        injected: list[tuple[str, int]] = []
        for kind, rnd, client, scale in self.specs:
            if rnd is not None and rnd != round_idx:
                continue
            codes[client] = FAULT_CODES[kind]
            scales[client] = np.float32(scale)
            injected.append((kind, client))
        return RoundFaults(
            weight_mask=mask,
            codes=codes,
            scales=scales,
            dropped=tuple(dropped),
            straggled=tuple(straggled),
            injected=tuple(injected),
        )

    def batch_keys(self, round_idx: int) -> dict[str, np.ndarray]:
        """The per-client fault vectors a chaos-enabled step expects in
        every batch dict (``train.step`` applies them at the update
        boundary)."""
        rf = self.round_faults(round_idx)
        return {"chaos.code": rf.codes, "chaos.scale": rf.scales}

    # ---------------------------------------------- population-level faults
    def is_flaky(self, client_id: int) -> bool:
        """Whether a LOGICAL client belongs to the seeded flaky cohort —
        a fixed ``pop_flaky_fraction`` subset of the population whose
        per-round dropout probability is ``pop_flaky_drop_rate`` instead
        of ``pop_drop_rate`` (chronically bad connectivity, not bad
        luck). Pure in ``(seed, client_id)``: flakiness is a property of
        the client, stable across rounds and replays."""
        frac = float(getattr(self.cfg, "pop_flaky_fraction", 0.0))
        if frac <= 0:
            return False
        u = np.random.default_rng([self.seed, int(client_id), 0xF1A]).random()
        return bool(u < frac)

    def population_report(
        self, round_idx: int, client_ids, attempt: int = 0
    ) -> tuple[np.ndarray, np.ndarray]:
        """Simulate one round's reporting behavior for sampled LOGICAL
        clients: ``(dropped, latency_ms)`` — ``dropped[i]`` True when
        client ``client_ids[i]`` never starts (over-selection's target),
        ``latency_ms[i]`` its simulated report latency (the round
        deadline's target; 0 when ``pop_straggle_ms`` is off).

        Deterministic per ``(seed, round_idx, attempt, client_id)``: the
        same client gets the same fate in both the cohort-packing draw and
        the per-round weight computation, replays are bit-identical, and a
        quorum re-draw (``attempt`` bump) rolls genuinely fresh dice.
        """
        return population_report(self, round_idx, client_ids, attempt)


def rejoin_holdoff(chaos_cfg: Any, worker_id: int, marker_dir) -> float:
    """Kill->shrink->rejoin scripting for the elastic deployment: the
    seconds a respawned, chaos-killed worker should wait BEFORE rejoining
    the membership service (``chaos.rejoin_delay_s``), or 0.

    Marker-guarded like the kill itself: only the worker named by
    ``chaos.kill_process``, only AFTER its kill marker exists (it actually
    died), and only ONCE (``chaos_rejoin_delayed_p<ID>`` written on the
    first holdoff) — later reform-driven respawns of the same worker
    rejoin immediately. The holdoff is what makes the shrink epoch
    observable before the rejoin epoch: without it a fast respawn races
    straight back into the survivors' formation window and the world
    re-forms at full size in one step.
    """
    from pathlib import Path

    if (
        not getattr(chaos_cfg, "enabled", False)
        or float(getattr(chaos_cfg, "rejoin_delay_s", 0.0)) <= 0
        or int(getattr(chaos_cfg, "kill_process", -1)) != int(worker_id)
    ):
        return 0.0
    marker_dir = Path(marker_dir)
    killed = marker_dir / f"chaos_killed_p{int(worker_id)}"
    delayed = marker_dir / f"chaos_rejoin_delayed_p{int(worker_id)}"
    if not killed.exists() or delayed.exists():
        return 0.0
    marker_dir.mkdir(parents=True, exist_ok=True)
    delayed.write_text(str(chaos_cfg.rejoin_delay_s))
    return float(chaos_cfg.rejoin_delay_s)


def population_report(
    plan: "FaultPlan | None", round_idx: int, client_ids, attempt: int = 0
) -> tuple[np.ndarray, np.ndarray]:
    """Module-level variant tolerating ``plan=None`` (chaos disabled):
    nobody drops, everybody reports instantly."""
    ids = np.asarray(client_ids, np.int64)
    dropped = np.zeros(ids.shape, bool)
    latency = np.zeros(ids.shape, np.float64)
    if plan is None:
        return dropped, latency
    cfg = plan.cfg
    drop_rate = float(getattr(cfg, "pop_drop_rate", 0.0))
    flaky_rate = float(getattr(cfg, "pop_flaky_drop_rate", 0.5))
    straggle_ms = float(getattr(cfg, "pop_straggle_ms", 0.0))
    straggle_sigma = float(getattr(cfg, "pop_straggle_sigma", 1.0))
    any_flaky = float(getattr(cfg, "pop_flaky_fraction", 0.0)) > 0
    if drop_rate <= 0 and not any_flaky and straggle_ms <= 0:
        return dropped, latency
    for i, cid in enumerate(ids):
        rng = np.random.default_rng(
            [plan.seed, int(round_idx), int(attempt), int(cid), 0x90B]
        )
        p = flaky_rate if (any_flaky and plan.is_flaky(int(cid))) else drop_rate
        dropped[i] = rng.random() < p
        if straggle_ms > 0:
            # lognormal with median = pop_straggle_ms: half the population
            # reports faster, the heavy tail is what deadlines cut
            latency[i] = straggle_ms * rng.lognormal(0.0, straggle_sigma)
    return dropped, latency
