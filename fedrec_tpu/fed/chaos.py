"""Deterministic fault injection: a seeded plan of client & host faults.

At production scale client failure is the steady state, not the exception
(FedJAX, arxiv 2108.02117, treats client subsampling/failure as a
first-class simulation primitive) — so the robustness machinery needs a
way to be *exercised*, reproducibly. A :class:`FaultPlan` is a pure
function of ``(chaos config, round index)``: the same plan produces the
same faults on every run and on every rollback replay, so

* a chaos run is bit-identical when re-run (the acceptance bar for the
  chaos smoke), and
* the Trainer's quarantine/rollback replay re-encounters the exact fault
  it rolled back from, proving the quarantine — not luck — saved the
  round.

Client-side faults come in two flavors:

* **participation faults** (``drop``, ``straggle``): the client's round
  weight is forced to 0 — it trains but its contribution is excluded,
  exactly the failure mode the reference dies on
  (Final_Report.pdf VII.a). Stragglers can additionally cost a host-side
  delay (``straggle_ms``) on the host-driven path.
* **update faults** (``nan``, ``scale``, ``flip``): applied as masks at
  the optimizer-update boundary INSIDE the jitted step. The per-client
  ``(code, scale)`` vectors ride the batch dict as ``chaos.code`` /
  ``chaos.scale`` arrays, so every dispatch mode (per-batch, epoch scan,
  rounds-in-jit) compiles the same fault arithmetic, and the flight
  recorder's batch ring captures them — ``fedrec-obs replay`` re-injects
  the fault for free.

Host-level faults (``kill_round``/``kill_process``, guarded by an
on-disk marker so a resumed world doesn't re-die; ``torn_snapshot_round``)
live in the coordinator CLI, which reads the same config section.

**Wire-level faults** (``chaos.wire_faults`` + ``chaos.wire_seed``)
exercise the TRANSPORT instead of the update math: a seeded
:class:`WireFaultPlan` drives a :class:`ChaosProxy` — a TCP
man-in-the-middle fronting the commit authority or membership service —
that drops, delays, tears mid-message, duplicates, or fully partitions
the one-shot JSON-lines exchanges passing through it, per connection and
per time window.  Fault draws are pure in ``(wire_seed, connection
index)``, so a churn soak's fault schedule replays bit-identically; with
no plan (or outside every window) the proxy forwards every byte
VERBATIM — the passthrough is pinned byte-identical in
``tests/test_rpc.py``, so chaos-off runs cannot differ by construction.
"""

from __future__ import annotations

import socket
import threading
import time
from dataclasses import dataclass
from typing import Any

import numpy as np

# update-fault codes carried in the batch's chaos.code vector; 0 = none
FAULT_CODES = {"nan": 1, "scale": 2, "flip": 3}


@dataclass(frozen=True)
class RoundFaults:
    """One round's resolved faults (pure function of plan + round)."""

    weight_mask: np.ndarray            # (C,) float32 0/1 — drop+straggle
    codes: np.ndarray                  # (C,) int32 update-fault codes
    scales: np.ndarray                 # (C,) float32 (code==scale multiplier)
    dropped: tuple = ()
    straggled: tuple = ()
    injected: tuple = ()               # ((kind, client), ...) update faults

    @property
    def any(self) -> bool:
        return bool(
            self.dropped or self.straggled or self.injected
        )


def parse_faults(spec: str, num_clients: int) -> list[tuple[str, int | None, int, float]]:
    """Parse the ``faults`` DSL: comma list of ``kind@round:client[xscale]``
    (``round`` may be ``*`` = every round). Raises on malformed entries so a
    typo'd plan fails at build time, not silently fault-free."""
    out: list[tuple[str, int | None, int, float]] = []
    for item in spec.split(","):
        item = item.strip()
        if not item:
            continue
        try:
            kind, rest = item.split("@", 1)
            round_s, client_s = rest.split(":", 1)
            scale = 1.0
            if "x" in client_s:
                client_s, scale_s = client_s.split("x", 1)
                scale = float(scale_s)
            rnd = None if round_s == "*" else int(round_s)
            client = int(client_s)
        except ValueError:
            raise ValueError(
                f"chaos.faults entry {item!r} is not "
                "'kind@round:client[xscale]' (e.g. 'nan@2:3,scale@*:5x100')"
            ) from None
        if kind not in FAULT_CODES:
            raise ValueError(
                f"chaos.faults entry {item!r}: unknown kind {kind!r}; "
                f"expected one of {sorted(FAULT_CODES)}"
            )
        if not 0 <= client < num_clients:
            raise ValueError(
                f"chaos.faults entry {item!r}: client {client} out of range "
                f"[0, {num_clients})"
            )
        out.append((kind, rnd, client, scale))
    return out


class FaultPlan:
    """Seeded, deterministic per-round fault schedule.

    ``round_faults(r)`` is idempotent: the random drop/straggle draws are
    derived from ``default_rng([seed, r])``, never from mutable state, so
    rollback replays and re-runs see identical faults.
    """

    def __init__(self, chaos_cfg: Any, num_clients: int):
        self.cfg = chaos_cfg
        self.num_clients = int(num_clients)
        self.seed = int(chaos_cfg.seed)
        self.drop_rate = float(chaos_cfg.drop_rate)
        self.straggle_rate = float(chaos_cfg.straggle_rate)
        self.specs = parse_faults(chaos_cfg.faults, self.num_clients)

    def round_faults(self, round_idx: int) -> RoundFaults:
        c = self.num_clients
        mask = np.ones((c,), np.float32)
        dropped: list[int] = []
        straggled: list[int] = []
        if self.drop_rate > 0 or self.straggle_rate > 0:
            rng = np.random.default_rng([self.seed, int(round_idx)])
            u = rng.random(c)
            # one draw decides both: [0, drop) drops, [drop, drop+straggle)
            # straggles — so the rates compose without double-failing
            for i in range(c):
                if u[i] < self.drop_rate:
                    dropped.append(i)
                    mask[i] = 0.0
                elif u[i] < self.drop_rate + self.straggle_rate:
                    straggled.append(i)
                    mask[i] = 0.0
        codes = np.zeros((c,), np.int32)
        scales = np.ones((c,), np.float32)
        injected: list[tuple[str, int]] = []
        for kind, rnd, client, scale in self.specs:
            if rnd is not None and rnd != round_idx:
                continue
            codes[client] = FAULT_CODES[kind]
            scales[client] = np.float32(scale)
            injected.append((kind, client))
        return RoundFaults(
            weight_mask=mask,
            codes=codes,
            scales=scales,
            dropped=tuple(dropped),
            straggled=tuple(straggled),
            injected=tuple(injected),
        )

    def batch_keys(self, round_idx: int) -> dict[str, np.ndarray]:
        """The per-client fault vectors a chaos-enabled step expects in
        every batch dict (``train.step`` applies them at the update
        boundary)."""
        rf = self.round_faults(round_idx)
        return {"chaos.code": rf.codes, "chaos.scale": rf.scales}

    # ---------------------------------------------- population-level faults
    def is_flaky(self, client_id: int) -> bool:
        """Whether a LOGICAL client belongs to the seeded flaky cohort —
        a fixed ``pop_flaky_fraction`` subset of the population whose
        per-round dropout probability is ``pop_flaky_drop_rate`` instead
        of ``pop_drop_rate`` (chronically bad connectivity, not bad
        luck). Pure in ``(seed, client_id)``: flakiness is a property of
        the client, stable across rounds and replays."""
        frac = float(getattr(self.cfg, "pop_flaky_fraction", 0.0))
        if frac <= 0:
            return False
        u = np.random.default_rng([self.seed, int(client_id), 0xF1A]).random()
        return bool(u < frac)

    def population_report(
        self, round_idx: int, client_ids, attempt: int = 0
    ) -> tuple[np.ndarray, np.ndarray]:
        """Simulate one round's reporting behavior for sampled LOGICAL
        clients: ``(dropped, latency_ms)`` — ``dropped[i]`` True when
        client ``client_ids[i]`` never starts (over-selection's target),
        ``latency_ms[i]`` its simulated report latency (the round
        deadline's target; 0 when ``pop_straggle_ms`` is off).

        Deterministic per ``(seed, round_idx, attempt, client_id)``: the
        same client gets the same fate in both the cohort-packing draw and
        the per-round weight computation, replays are bit-identical, and a
        quorum re-draw (``attempt`` bump) rolls genuinely fresh dice.
        """
        return population_report(self, round_idx, client_ids, attempt)


def rejoin_holdoff(chaos_cfg: Any, worker_id: int, marker_dir) -> float:
    """Kill->shrink->rejoin scripting for the elastic deployment: the
    seconds a respawned, chaos-killed worker should wait BEFORE rejoining
    the membership service (``chaos.rejoin_delay_s``), or 0.

    Marker-guarded like the kill itself: only the worker named by
    ``chaos.kill_process``, only AFTER its kill marker exists (it actually
    died), and only ONCE (``chaos_rejoin_delayed_p<ID>`` written on the
    first holdoff) — later reform-driven respawns of the same worker
    rejoin immediately. The holdoff is what makes the shrink epoch
    observable before the rejoin epoch: without it a fast respawn races
    straight back into the survivors' formation window and the world
    re-forms at full size in one step.
    """
    from pathlib import Path

    if (
        not getattr(chaos_cfg, "enabled", False)
        or float(getattr(chaos_cfg, "rejoin_delay_s", 0.0)) <= 0
        or int(getattr(chaos_cfg, "kill_process", -1)) != int(worker_id)
    ):
        return 0.0
    marker_dir = Path(marker_dir)
    killed = marker_dir / f"chaos_killed_p{int(worker_id)}"
    delayed = marker_dir / f"chaos_rejoin_delayed_p{int(worker_id)}"
    if not killed.exists() or delayed.exists():
        return 0.0
    marker_dir.mkdir(parents=True, exist_ok=True)
    delayed.write_text(str(chaos_cfg.rejoin_delay_s))
    return float(chaos_cfg.rejoin_delay_s)


def population_report(
    plan: "FaultPlan | None", round_idx: int, client_ids, attempt: int = 0
) -> tuple[np.ndarray, np.ndarray]:
    """Module-level variant tolerating ``plan=None`` (chaos disabled):
    nobody drops, everybody reports instantly."""
    ids = np.asarray(client_ids, np.int64)
    dropped = np.zeros(ids.shape, bool)
    latency = np.zeros(ids.shape, np.float64)
    if plan is None:
        return dropped, latency
    cfg = plan.cfg
    drop_rate = float(getattr(cfg, "pop_drop_rate", 0.0))
    flaky_rate = float(getattr(cfg, "pop_flaky_drop_rate", 0.5))
    straggle_ms = float(getattr(cfg, "pop_straggle_ms", 0.0))
    straggle_sigma = float(getattr(cfg, "pop_straggle_sigma", 1.0))
    any_flaky = float(getattr(cfg, "pop_flaky_fraction", 0.0)) > 0
    if drop_rate <= 0 and not any_flaky and straggle_ms <= 0:
        return dropped, latency
    for i, cid in enumerate(ids):
        rng = np.random.default_rng(
            [plan.seed, int(round_idx), int(attempt), int(cid), 0x90B]
        )
        p = flaky_rate if (any_flaky and plan.is_flaky(int(cid))) else drop_rate
        dropped[i] = rng.random() < p
        if straggle_ms > 0:
            # lognormal with median = pop_straggle_ms: half the population
            # reports faster, the heavy tail is what deadlines cut
            latency[i] = straggle_ms * rng.lognormal(0.0, straggle_sigma)
    return dropped, latency


# ======================================================================
# wire-level fault injection (chaos.wire_faults): seeded network faults
# applied by a chaos TCP proxy fronting a JSON-lines service
# ======================================================================

# transport fault kinds and their default argument (probability for
# drop, milliseconds for delay, copies for dup; tear/partition take none)
WIRE_FAULT_KINDS = {
    "drop": 1.0,        # refuse the connection (arg = probability)
    "delay": 100.0,     # hold the request this many ms before forwarding
    "tear": 0.0,        # forward HALF the request bytes, then hang up
    "dup": 2.0,         # deliver the request arg times upstream
    "partition": 0.0,   # full partition: nothing gets through the window
}


def parse_wire_faults(spec: str) -> list[tuple[str, float, float, float]]:
    """Parse the ``chaos.wire_faults`` DSL: comma list of
    ``kind@start[-end][:arg]`` — ``start``/``end`` are seconds since the
    proxy started, ``*`` means always, a single time ``t`` means the
    one-second window ``[t, t+1)``.  Returns ``(kind, start_s, end_s,
    arg)`` tuples; raises ``ValueError`` on malformed entries so a
    typo'd plan fails at build time, not silently fault-free."""
    out: list[tuple[str, float, float, float]] = []
    for item in spec.split(","):
        item = item.strip()
        if not item:
            continue
        try:
            kind, rest = item.split("@", 1)
            arg_s = None
            if ":" in rest:
                rest, arg_s = rest.split(":", 1)
            if rest == "*":
                start, end = 0.0, float("inf")
            elif "-" in rest:
                start_s, end_s = rest.split("-", 1)
                start, end = float(start_s), float(end_s)
            else:
                start = float(rest)
                end = start + 1.0
            arg = (
                float(arg_s) if arg_s is not None
                else WIRE_FAULT_KINDS.get(kind, 0.0)
            )
        except ValueError:
            raise ValueError(
                f"chaos.wire_faults entry {item!r} is not "
                "'kind@start[-end][:arg]' (e.g. 'tear@2-4,dup@5-8,"
                "partition@20-30,drop@*:0.3')"
            ) from None
        if kind not in WIRE_FAULT_KINDS:
            raise ValueError(
                f"chaos.wire_faults entry {item!r}: unknown kind {kind!r}; "
                f"expected one of {sorted(WIRE_FAULT_KINDS)}"
            )
        if end <= start:
            raise ValueError(
                f"chaos.wire_faults entry {item!r}: empty window "
                f"[{start:g}, {end:g})"
            )
        out.append((kind, start, end, arg))
    return out


class WireFaultPlan:
    """Seeded, deterministic wire-fault schedule for one proxy.

    ``actions(t_s, conn_idx)`` resolves which faults apply to the
    ``conn_idx``-th accepted connection at ``t_s`` seconds since proxy
    start.  Probabilistic draws (``drop`` with ``arg < 1``) come from
    ``default_rng([seed, conn_idx])`` — pure in the inputs, so the same
    soak re-runs against the identical fault schedule."""

    def __init__(self, spec: str, seed: int = 0):
        self.spec = str(spec)
        self.seed = int(seed)
        self.entries = parse_wire_faults(self.spec)

    def actions(self, t_s: float, conn_idx: int) -> list[tuple[str, float]]:
        out: list[tuple[str, float]] = []
        rng = None
        for kind, start, end, arg in self.entries:
            if not start <= t_s < end:
                continue
            if kind == "drop" and arg < 1.0:
                if rng is None:
                    rng = np.random.default_rng([self.seed, int(conn_idx)])
                if rng.random() >= arg:
                    continue
            out.append((kind, arg))
        return out


class ChaosProxy:
    """A chaos TCP man-in-the-middle for one-shot JSON-lines exchanges.

    Listens on ``address`` and forwards each accepted connection's
    single request line to ``upstream``, then the reply line back —
    BYTE-VERBATIM when no fault applies (pinned in tests/test_rpc.py:
    chaos off can never change the wire).  When the plan fires:

    * ``partition`` / ``drop`` — the client's connection is closed
      before any byte crosses (a black-holed edge),
    * ``delay`` — the request is held ``arg`` ms before forwarding,
    * ``tear`` — HALF the request bytes reach the upstream, then both
      sides are hung up (the torn-mid-message case the push ledger and
      same-(worker, round) replacement must absorb),
    * ``dup`` — the request is delivered ``arg`` times as separate
      upstream exchanges; the client gets the FIRST reply (duplicated
      delivery after a lost ack — the idempotent ``push_id`` case).

    Faults count into ``chaos.wire_faults_total`` (labelled by kind) and
    the local ``injected`` dict for artifact banking."""

    _POLL_S = 0.2

    def __init__(
        self,
        upstream_host: str,
        upstream_port: int,
        plan: WireFaultPlan | None = None,
        host: str = "127.0.0.1",
        port: int = 0,
        timeout_s: float = 30.0,
    ):
        self.upstream = (str(upstream_host), int(upstream_port))
        self.plan = plan
        self.timeout_s = float(timeout_s)
        self.injected: dict[str, int] = {}
        self._sock = socket.create_server((host, int(port)))
        self._sock.settimeout(self._POLL_S)
        self.host, self.port = self._sock.getsockname()[:2]
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._conn_idx = 0
        self._t0 = time.monotonic()

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    # ------------------------------------------------------------ lifecycle
    def start(self) -> "ChaosProxy":
        self._t0 = time.monotonic()
        self._thread = threading.Thread(
            target=self._accept_loop, name="chaos-proxy", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        try:
            self._sock.close()
        except OSError:
            pass

    # ------------------------------------------------------------- plumbing
    def _count(self, kind: str) -> None:
        self.injected[kind] = self.injected.get(kind, 0) + 1
        from fedrec_tpu.obs import get_registry

        get_registry().counter(
            "chaos.wire_faults_total",
            "transport faults the chaos proxy injected, by kind "
            "(seeded plan: chaos.wire_faults / chaos.wire_seed)",
            labels=("kind",),
        ).inc(kind=kind)

    @staticmethod
    def _read_line(conn: socket.socket) -> bytes:
        """The full request (through its newline) as raw bytes."""
        buf = b""
        while b"\n" not in buf:
            chunk = conn.recv(1 << 20)
            if not chunk:
                break
            buf += chunk
        return buf

    def _exchange_upstream(self, payload: bytes) -> bytes:
        with socket.create_connection(
            self.upstream, timeout=self.timeout_s
        ) as up:
            up.settimeout(self.timeout_s)
            up.sendall(payload)
            return self._read_line(up)

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            idx, self._conn_idx = self._conn_idx, self._conn_idx + 1
            t_s = time.monotonic() - self._t0
            threading.Thread(
                target=self._handle, args=(conn, idx, t_s), daemon=True
            ).start()

    def _handle(self, conn: socket.socket, idx: int, t_s: float) -> None:
        actions = (
            dict(self.plan.actions(t_s, idx)) if self.plan is not None else {}
        )
        try:
            with conn:
                conn.settimeout(self.timeout_s)
                if "partition" in actions or "drop" in actions:
                    # black hole: the client sees a reset/empty reply and
                    # its resilient RPC retries into the backoff budget
                    self._count(
                        "partition" if "partition" in actions else "drop"
                    )
                    return
                payload = self._read_line(conn)
                if not payload:
                    return
                if "delay" in actions:
                    self._count("delay")
                    time.sleep(actions["delay"] / 1e3)
                if "tear" in actions:
                    # half the request reaches the peer, then both sides
                    # hang up: the peer sees no full line (sends nothing),
                    # the client sees an ack-less close (OSError)
                    self._count("tear")
                    try:
                        with socket.create_connection(
                            self.upstream, timeout=self.timeout_s
                        ) as up:
                            up.sendall(payload[: max(len(payload) // 2, 1)])
                    except OSError:
                        pass
                    return
                copies = int(actions.get("dup", 1)) if "dup" in actions else 1
                if copies > 1:
                    self._count("dup")
                reply = b""
                for i in range(max(copies, 1)):
                    try:
                        got = self._exchange_upstream(payload)
                    except OSError:
                        got = b""
                    if i == 0:
                        reply = got
                if reply:
                    conn.sendall(reply)
        except OSError:
            pass
