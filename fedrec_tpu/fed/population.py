"""Logical-client population for cross-device federation.

The reference (and every PR up to 5) federates a handful of always-on
"clients" that ARE the device slots — cross-silo at toy scale. Real
cross-device federation (FedJAX, arxiv 2108.02117) trains a population of
N >> devices *logical* clients: each round a cohort is sampled onto the
fixed mesh, trains its own data shard, and reports — or doesn't. This
module is the host-side client-state layer behind ``fed.population``:

* :class:`ClientPopulation` — N logical clients, each owning

  - a **data-shard handle**: a static, seeded, equal-size row shard of the
    training set (equal sizes keep the per-round step count static, the
    contract every jitted dispatch mode relies on);
  - a **sample count** (the ``weighted`` sampler's selection weight);
  - an **optimizer sidecar** where the strategy keeps one
    (``client_state="persist"``): the non-parameter slot leaves — optax
    states, PRNG key, step counter, decoupled-mode grad accumulator —
    written back when the client rotates out of its slot and reloaded on
    its next selection. Kept host-side in an LRU-bounded dict and spilled
    to disk above ``resident_cap`` (``spill_dir``), so population size is
    bounded by disk, not host RAM;
  - a **participation ledger** row: selected / reported / dropped /
    deadline-cut counters plus the quarantine expiry, serialized into
    snapshots so a resumed run continues the identical schedule.

* :class:`CohortPlan` + :func:`build_cohort_plan` — one round's resolved
  cohort: ``ceil(slots * over_select)`` sampled candidates
  (priority-ordered), the chaos-simulated dropouts removed, the survivors
  packed front-to-back into the device slots, short cohorts padded by
  repeating survivors with weight 0 (static shapes; pads never write
  back).

* :func:`plan_round_weights` — one round's per-slot participation
  weights: 0 for pads, per-round dropouts, and clients whose simulated
  report latency exceeds the round deadline (the deadline-cut). The same
  ``(seed, round, attempt, client)`` derivation as the packing step, so
  the two views of a client's fate can never disagree.

* :exc:`QuorumFailure` — raised when a round's reporting count falls
  below ``min_reports``; the Trainer discards the round from its entry
  state and replays with a fresh draw (``attempt`` + 1), bounded by
  ``quorum_retries``.

Everything here is host-side numpy: the device program is untouched — a
sampled-world round compiles to exactly the fixed-world program, fed a
different batch stack and weight vector.
"""

from __future__ import annotations

from collections import OrderedDict
from dataclasses import dataclass
from pathlib import Path
from typing import Any, Iterator

import numpy as np

# the non-parameter ClientState slot leaves that follow a LOGICAL client
# across selections: optax states, PRNG key, step counter, decoupled-mode
# grad accumulator, and the update codec's error-feedback residual
# (fed.dcn_compress sign1bit/topk — a healed or fresh client starts from
# the all-zero template residual, same contract as the optimizer moments)
SIDECAR_FIELDS = (
    "step", "opt_user", "opt_news", "rng", "news_grad_accum", "ef_residual",
)


class QuorumFailure(Exception):
    """A round's reporting cohort fell below ``fed.population.min_reports``.

    Control flow, not an error: the Trainer catches it BEFORE any state
    mutation (weights are computed at round entry), counts a quorum
    replay, and re-enters the round with ``attempt + 1`` — a fresh cohort
    draw and fresh fault dice — up to ``quorum_retries`` times, after
    which the run aborts with an operator-grade message.
    """

    def __init__(self, anchor_round: int, round_idx: int, reporting: int,
                 min_reports: int, attempt: int):
        super().__init__(
            f"round {round_idx}: {reporting} reporting clients < quorum "
            f"min_reports={min_reports} (draw attempt {attempt})"
        )
        self.anchor_round = int(anchor_round)  # the chunk's draw anchor
        self.round_idx = int(round_idx)
        self.reporting = int(reporting)
        self.attempt = int(attempt)


@dataclass
class CohortPlan:
    """One round's (or rounds-in-jit chunk's) resolved cohort."""

    round_idx: int                     # the draw anchor round
    attempt: int                       # quorum re-draw counter
    sampled: np.ndarray                # (S,) drawn candidates, priority order
    start_dropped: np.ndarray          # sampled ids that never started
    slot_clients: np.ndarray           # (slots,) logical occupant per slot
    slot_real: np.ndarray              # (slots,) bool; False = weight-0 pad

    @property
    def spares_unused(self) -> int:
        """Over-selected survivors that found no free slot."""
        survivors = len(self.sampled) - len(self.start_dropped)
        return max(0, survivors - int(self.slot_real.sum()))


def build_cohort_plan(
    sampler: Any,
    slots: int,
    round_idx: int,
    over_select: float,
    chaos: Any = None,
    exclude: set | tuple = (),
    attempt: int = 0,
    pack: bool = True,
) -> CohortPlan:
    """Sample and pack one round's cohort (see module docstring).

    ``pack=False`` is the fixed-world (population == slots) mode: slots
    ARE the clients, so over-selection repacking is skipped — a dropout
    keeps its slot and loses its weight in :func:`plan_round_weights`
    instead. This keeps the slot->client map identical no matter where
    the plan is anchored, which is what makes host-driven rounds and
    rounds-in-jit chunks (one plan per chunk) bit-identical under
    population-level chaos.
    """
    if over_select < 1.0:
        raise ValueError(
            f"fed.population.over_select must be >= 1.0, got {over_select}"
        )
    from fedrec_tpu.fed.chaos import population_report

    want = int(np.ceil(slots * over_select))
    sampled = sampler.draw(round_idx, want, exclude=exclude, attempt=attempt)
    if sampled.size == 0:
        raise RuntimeError(
            "cohort sampling found no eligible clients (population "
            "exhausted by quarantine?)"
        )
    if not pack:
        return CohortPlan(
            round_idx=int(round_idx),
            attempt=int(attempt),
            sampled=np.asarray(sampled, np.int64),
            start_dropped=np.zeros((0,), np.int64),
            slot_clients=np.resize(sampled, slots).astype(np.int64),
            slot_real=np.arange(slots) < len(sampled),
        )
    dropped, _ = population_report(chaos, round_idx, sampled, attempt)
    survivors = sampled[~dropped]
    if survivors.size == 0:
        # everyone sampled dropped: pad slots from the raw draw so shapes
        # stay static; every slot is weight-0 and the quorum policy (or
        # the zero-participation round contract) decides what happens
        occupants = sampled[:1]
    else:
        occupants = survivors[:slots]
    n_real = int(min(len(occupants), slots)) if survivors.size else 0
    slot_clients = np.resize(occupants, slots).astype(np.int64)
    slot_real = np.arange(slots) < n_real
    return CohortPlan(
        round_idx=int(round_idx),
        attempt=int(attempt),
        sampled=np.asarray(sampled, np.int64),
        start_dropped=np.asarray(sampled[dropped], np.int64),
        slot_clients=slot_clients,
        slot_real=slot_real,
    )


def plan_round_weights(
    plan: CohortPlan,
    round_idx: int,
    deadline_ms: float = 0.0,
    chaos: Any = None,
) -> tuple[np.ndarray, dict]:
    """(slots,) float32 participation weights for ``round_idx`` under
    ``plan``'s packing, plus an event dict for the ledger/metrics:
    ``{"reported": ids, "dropped": ids, "deadline_cut": ids}``.

    For the plan's anchor round the dropout draws REPLAY the packing
    draws (same rng keys), so an occupant can only lose weight to the
    deadline; later rounds of a rounds-in-jit chunk re-roll per-round
    fates for the fixed cohort.
    """
    from fedrec_tpu.fed.chaos import population_report

    slots = plan.slot_clients.shape[0]
    dropped, latency = population_report(
        chaos, round_idx, plan.slot_clients, plan.attempt
    )
    w = plan.slot_real & ~dropped
    cut = np.zeros(slots, bool)
    if deadline_ms and deadline_ms > 0:
        cut = w & (latency > deadline_ms)
        w = w & ~cut
    # a client padded into several slots must count (and weigh) once —
    # dedupe by first slot occurrence; pads are weight 0 anyway via
    # slot_real, so this only guards the degenerate everyone-dropped fill
    events = {
        "reported": _unique_ids(plan.slot_clients[w]),
        "dropped": _unique_ids(plan.slot_clients[plan.slot_real & dropped]),
        "deadline_cut": _unique_ids(plan.slot_clients[cut]),
    }
    return w.astype(np.float32), events


def _unique_ids(ids: np.ndarray) -> np.ndarray:
    return np.unique(np.asarray(ids, np.int64))


# --------------------------------------------------------------- ledger
class ParticipationLedger:
    """Per-logical-client participation bookkeeping + quarantine expiry."""

    def __init__(self, population: int):
        self.population = int(population)
        self.selected = np.zeros((population,), np.int64)
        self.reported = np.zeros((population,), np.int64)
        self.dropped = np.zeros((population,), np.int64)
        self.deadline_cut = np.zeros((population,), np.int64)
        # client id -> first round it may be sampled again
        self.quarantined: dict[int, int] = {}

    def commit(self, cohort: np.ndarray, events: dict) -> None:
        np.add.at(self.selected, np.asarray(cohort, np.int64), 1)
        for key, arr in (
            ("reported", self.reported),
            ("dropped", self.dropped),
            ("deadline_cut", self.deadline_cut),
        ):
            ids = np.asarray(events.get(key, ()), np.int64)
            if ids.size:
                np.add.at(arr, ids, 1)

    def quarantine(self, client_id: int, until_round: int) -> None:
        cid = int(client_id)
        self.quarantined[cid] = max(self.quarantined.get(cid, 0), int(until_round))

    def active_quarantine(self, round_idx: int) -> set[int]:
        """Clients still excluded at ``round_idx`` (expired entries pruned)."""
        expired = [c for c, until in self.quarantined.items()
                   if until <= round_idx]
        for c in expired:
            del self.quarantined[c]
        return set(self.quarantined)

    def coverage(self) -> float:
        """Fraction of the population selected at least once."""
        return float((self.selected > 0).mean())

    # -------------------------------------------------------- persistence
    def state_dict(self) -> dict:
        q_ids = np.asarray(sorted(self.quarantined), np.int64)
        return {
            "population": np.int64(self.population),
            "selected": self.selected.copy(),
            "reported": self.reported.copy(),
            "dropped": self.dropped.copy(),
            "deadline_cut": self.deadline_cut.copy(),
            "quarantine_ids": q_ids,
            "quarantine_until": np.asarray(
                [self.quarantined[int(c)] for c in q_ids], np.int64
            ),
        }

    def load_state_dict(self, state: dict, resize: bool = False) -> None:
        """Restore the ledger. ``resize=False`` (the default) demands an
        exact population match — a mismatch on a fixed-world resume is a
        config error. ``resize=True`` is the elastic-membership continuity
        mode: a sidecar saved under a DIFFERENT population size is adopted
        by copying the overlapping prefix of every counter (clients beyond
        the saved population start their history fresh; counters for
        clients that no longer exist are dropped) and keeping only the
        quarantine entries still addressable — participation history
        survives an epoch's slot rebalance instead of resetting to zero.
        """
        pop = int(state["population"])
        if pop != self.population and not resize:
            raise ValueError(
                f"ledger population mismatch: saved {pop} vs configured "
                f"{self.population}"
            )
        n = min(pop, self.population)
        for key in ("selected", "reported", "dropped", "deadline_cut"):
            arr = np.asarray(state[key], np.int64)
            if arr.shape != (pop,):
                raise ValueError(f"ledger {key} shape {arr.shape}")
            fresh = np.zeros((self.population,), np.int64)
            fresh[:n] = arr[:n]
            setattr(self, key, fresh)
        ids = np.asarray(state.get("quarantine_ids", ()), np.int64)
        until = np.asarray(state.get("quarantine_until", ()), np.int64)
        self.quarantined = {
            int(c): int(u)
            for c, u in zip(ids.reshape(-1), until.reshape(-1))
            if int(c) < self.population
        }


# ----------------------------------------------------------- population
class ClientPopulation:
    """N logical clients: data shards, sidecar store, ledger.

    ``shard_rows(i)`` is client *i*'s static row shard of the (local)
    training set: a seeded permutation dealt round-robin and truncated to
    the common ``shard_size = n_rows // N`` — equal sizes by construction
    (the static-step-count contract), disjoint, deterministic in
    ``(data_seed, N)``.
    """

    def __init__(
        self,
        num_clients: int,
        num_rows: int,
        data_seed: int = 0,
        batch_size: int = 0,
        resident_cap: int = 0,
        spill_dir: str | Path | None = None,
    ):
        if num_clients <= 0:
            raise ValueError(f"population num_clients must be > 0, got {num_clients}")
        self.num_clients = int(num_clients)
        self.num_rows = int(num_rows)
        self.data_seed = int(data_seed)
        self.shard_size = self.num_rows // self.num_clients
        if self.shard_size < 1:
            raise ValueError(
                f"population of {num_clients} clients over {num_rows} "
                "training rows leaves empty shards; shrink "
                "fed.population.num_clients or bring more data"
            )
        if batch_size and self.shard_size < batch_size:
            raise ValueError(
                f"per-client shard ({self.shard_size} rows = {num_rows} // "
                f"{num_clients}) is smaller than data.batch_size="
                f"{batch_size}: a selected client could not fill one step. "
                "Shrink the batch size or the population."
            )
        perm = np.random.default_rng([self.data_seed, 0x909]).permutation(
            self.num_rows
        )
        # round-robin deal, truncated to the common size, sorted for
        # locality of the underlying row gathers
        self._rows = np.stack([
            np.sort(perm[i :: self.num_clients][: self.shard_size])
            for i in range(self.num_clients)
        ])
        self.sample_counts = np.full((self.num_clients,), self.shard_size, np.int64)
        self.ledger = ParticipationLedger(self.num_clients)
        # sidecar store: cid -> list of host leaves; LRU above resident_cap
        self.resident_cap = int(resident_cap)
        self.spill_dir = Path(spill_dir) if spill_dir else None
        self._resident: OrderedDict[int, list] = OrderedDict()
        self._spilled: set[int] = set()
        self._treedef = None
        self.spill_count = 0
        # per-client indexed.take views — static per (indexed, cid), so
        # rebuilding them every epoch of every round is pure host latency
        # between device dispatches; LRU-bounded to a few cohorts' worth
        self._take_cache: OrderedDict[int, Any] = OrderedDict()
        self._take_cache_src: int | None = None

    # ------------------------------------------------------------- shards
    def shard_rows(self, client_id: int) -> np.ndarray:
        return self._rows[int(client_id)]

    def steps_per_epoch(self, batch_size: int) -> int:
        return max(self.shard_size // int(batch_size), 1)

    def client_seed(self, client_id: int) -> int:
        """Stable per-client batcher seed (shuffle + negative sampling)."""
        return (self.data_seed * 1_000_003 + 0x5EED + int(client_id)) % (2**31)

    def cohort_epoch_batches(
        self, cohort: np.ndarray, indexed: Any, data_cfg: Any, epoch_idx: int
    ) -> Iterator[Any]:
        """Stacked (slots, B, ...) batches where slot *j* iterates client
        ``cohort[j]``'s OWN shard — the cross-device replacement for
        ``TrainBatcher.epoch_batches_sharded``'s epoch-resharding of the
        whole corpus. Per-client order and negatives are keyed by
        ``(client_seed, epoch_idx)``, so a client revisited in a later
        round reshuffles, and the schedule is reproducible without any
        per-client visit counters (resume-friendly)."""
        from fedrec_tpu.data.batcher import Batch, TrainBatcher

        cohort = np.asarray(cohort, np.int64)
        iters = [
            TrainBatcher(
                self._client_view(int(cid), indexed, cap=4 * len(cohort)),
                data_cfg.batch_size,
                data_cfg.npratio,
                shuffle=data_cfg.shuffle,
                drop_remainder=True,
                seed=self.client_seed(cid),
            ).epoch_batches(epoch_idx)
            for cid in cohort
        ]
        for _ in range(self.steps_per_epoch(data_cfg.batch_size)):
            bs = [next(it) for it in iters]
            yield Batch(
                candidates=np.stack([b.candidates for b in bs]),
                history=np.stack([b.history for b in bs]),
                his_len=np.stack([b.his_len for b in bs]),
                labels=np.stack([b.labels for b in bs]),
            )

    def _client_view(self, cid: int, indexed: Any, cap: int) -> Any:
        """LRU-cached ``indexed.take(shard_rows(cid))`` (invalidated if a
        different ``indexed`` object arrives — one population serves one
        training set)."""
        if self._take_cache_src is not id(indexed):
            self._take_cache.clear()
            self._take_cache_src = id(indexed)
        view = self._take_cache.get(cid)
        if view is None:
            view = indexed.take(self.shard_rows(cid))
            self._take_cache[cid] = view
        else:
            self._take_cache.move_to_end(cid)
        while len(self._take_cache) > max(int(cap), 8):
            self._take_cache.popitem(last=False)
        return view

    # ----------------------------------------------------------- sidecars
    def _spill_path(self, client_id: int) -> Path:
        assert self.spill_dir is not None
        return self.spill_dir / f"client_{int(client_id):08d}.npz"

    def put_sidecar(self, client_id: int, sidecar: Any) -> None:
        """Store a client's sidecar pytree (host arrays), evicting the
        least-recently-stored resident to disk above ``resident_cap``."""
        import jax

        cid = int(client_id)
        leaves, treedef = jax.tree_util.tree_flatten(sidecar)
        if self._treedef is None:
            self._treedef = treedef
        elif treedef != self._treedef:
            raise ValueError("sidecar pytree structure changed mid-run")
        self._resident[cid] = [np.asarray(x) for x in leaves]
        self._resident.move_to_end(cid)
        self._spilled.discard(cid)
        if self.resident_cap > 0:
            while len(self._resident) > self.resident_cap:
                old_cid, old_leaves = self._resident.popitem(last=False)
                self._spill(old_cid, old_leaves)

    def _spill(self, cid: int, leaves: list) -> None:
        if self.spill_dir is None:
            raise ValueError(
                "fed.population.resident_cap is set but no spill_dir is "
                "available (set fed.population.spill_dir or a snapshot dir)"
            )
        self.spill_dir.mkdir(parents=True, exist_ok=True)
        tmp = self._spill_path(cid).with_suffix(".npz.tmp")
        with open(tmp, "wb") as f:  # handle: np.savez would append .npz
            np.savez(f, **{f"leaf_{i}": x for i, x in enumerate(leaves)})
        tmp.replace(self._spill_path(cid))
        self._spilled.add(cid)
        self.spill_count += 1

    def get_sidecar(self, client_id: int) -> Any | None:
        """The client's stored sidecar pytree, or None if it was never
        stored (first selection: the caller supplies the template)."""
        import jax

        cid = int(client_id)
        if cid in self._resident:
            self._resident.move_to_end(cid)
            leaves = self._resident[cid]
            return jax.tree_util.tree_unflatten(self._treedef, list(leaves))
        if cid in self._spilled:
            with np.load(self._spill_path(cid)) as z:
                leaves = [z[f"leaf_{i}"] for i in range(len(z.files))]
            return jax.tree_util.tree_unflatten(self._treedef, leaves)
        return None

    def reset_sidecar(self, client_id: int) -> None:
        """Forget a client's stored sidecar (quarantine healing: its next
        selection restarts from the template)."""
        cid = int(client_id)
        self._resident.pop(cid, None)
        if cid in self._spilled:
            self._spilled.discard(cid)
            try:
                self._spill_path(cid).unlink()
            except OSError:
                pass

    @property
    def resident_sidecars(self) -> int:
        return len(self._resident)
