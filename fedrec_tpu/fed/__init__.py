from fedrec_tpu.fed.strategies import (
    FedStrategy,
    GradAvg,
    Local,
    ParamAvg,
    get_strategy,
    participation_mask,
    weighted_param_avg,
)

__all__ = [
    "FedStrategy",
    "GradAvg",
    "Local",
    "ParamAvg",
    "get_strategy",
    "participation_mask",
    "weighted_param_avg",
]
