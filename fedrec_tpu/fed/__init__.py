from fedrec_tpu.fed.strategies import (
    FedStrategy,
    GradAvg,
    Local,
    ParamAvg,
    get_strategy,
    participation_mask,
    weighted_param_avg,
)
from fedrec_tpu.fed.robust import (
    ROBUST_METHODS,
    robust_aggregate,
    robust_reduce_np,
    robust_reduce_tree_np,
    validate_robust_method,
)
from fedrec_tpu.fed.chaos import (
    FAULT_CODES,
    FaultPlan,
    RoundFaults,
    parse_faults,
    population_report,
)
from fedrec_tpu.fed.population import (
    ClientPopulation,
    CohortPlan,
    ParticipationLedger,
    QuorumFailure,
    build_cohort_plan,
    plan_round_weights,
)
from fedrec_tpu.fed.sampling import (
    SAMPLER_MODES,
    CohortSampler,
    validate_sampler_mode,
)

__all__ = [
    "FAULT_CODES",
    "ClientPopulation",
    "CohortPlan",
    "CohortSampler",
    "FaultPlan",
    "FedStrategy",
    "GradAvg",
    "Local",
    "ParamAvg",
    "ParticipationLedger",
    "QuorumFailure",
    "ROBUST_METHODS",
    "RoundFaults",
    "SAMPLER_MODES",
    "build_cohort_plan",
    "get_strategy",
    "parse_faults",
    "participation_mask",
    "plan_round_weights",
    "population_report",
    "robust_aggregate",
    "robust_reduce_np",
    "robust_reduce_tree_np",
    "validate_robust_method",
    "validate_sampler_mode",
    "weighted_param_avg",
]
