from fedrec_tpu.fed.strategies import (
    FedStrategy,
    GradAvg,
    Local,
    ParamAvg,
    get_strategy,
    participation_mask,
    weighted_param_avg,
)
from fedrec_tpu.fed.robust import (
    ROBUST_METHODS,
    robust_aggregate,
    robust_reduce_np,
    robust_reduce_tree_np,
    validate_robust_method,
)
from fedrec_tpu.fed.chaos import FAULT_CODES, FaultPlan, RoundFaults, parse_faults

__all__ = [
    "FAULT_CODES",
    "FaultPlan",
    "FedStrategy",
    "GradAvg",
    "Local",
    "ParamAvg",
    "ROBUST_METHODS",
    "RoundFaults",
    "get_strategy",
    "parse_faults",
    "participation_mask",
    "robust_aggregate",
    "robust_reduce_np",
    "robust_reduce_tree_np",
    "validate_robust_method",
    "weighted_param_avg",
]
