"""Byzantine-robust aggregation: the *reaction* half of training robustness.

PR 4's health sentry made a poisoned or diverging client *visible*
(per-client grad/update norms, outlier flags) — but the aggregator still
blended its update into everyone's parameters: ``weighted_param_avg`` is a
weighted mean, and a single ×1000-scaled contribution moves the mean by
×1000/n. This module supplies aggregators with bounded (or zero)
sensitivity to any one client, selectable via ``fed.robust.method``:

* ``mean``         — the existing participation-weighted FedAvg
  (``fedrec_tpu.fed.strategies.weighted_param_avg``); kept as the default
  and bit-identical to pre-robust behavior.
* ``clip``         — norm-clipped mean: each client's deviation from the
  coordinate-wise cohort *median* (a robust center available in-graph,
  unlike the round-start global) is clipped to ``clip_norm`` in global L2
  over the whole aggregated tree, then weighted-mean'd around the center.
  One client moves the aggregate by at most ``w_c * clip_norm / Σw`` —
  and a non-finite contribution clips to exactly zero.
* ``trimmed_mean`` — coordinate-wise: among *finite participant* values,
  drop the ``trim_k`` largest and smallest, mean the rest (unweighted
  over the kept participants, the standard definition — ``trim_k`` is
  clamped per-coordinate so at least one value is always kept).
* ``median``       — coordinate-wise median over finite participants.

All four run INSIDE the jitted round-end sync (``shard_map`` over the
cohort axes), so they compose with everything already in the program: DP
noise is applied per client *before* the sync, FedOpt steps the
post-aggregation global, and the rounds-in-jit scan carries the same
sync body as the host-driven round (``train.step._make_local_sync``).

Cost note: the robust methods materialize the full cohort per device via
``lax.all_gather`` — n_clients × params transient memory. Fine for the
cohort sizes federation simulates per chip (8–64 clients); the
coordinator's cross-host gather uses the numpy variant below on arrays
``process_allgather`` already materializes.

Non-participants (weight 0) are excluded from every method — which also
makes quarantine effective: a quarantined client whose parameters are NaN
contributes nothing, not NaN, to any aggregate (including ``mean``, whose
``weighted_param_avg`` masks zero-weight contributions for this reason).
A round with NO participants keeps local parameters, same contract as
``weighted_param_avg``.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

ROBUST_METHODS = ("mean", "clip", "trimmed_mean", "median")


def validate_robust_method(method: str) -> str:
    if method not in ROBUST_METHODS:
        raise ValueError(
            f"unknown fed.robust.method {method!r}; expected one of "
            f"{ROBUST_METHODS}"
        )
    return method


# --------------------------------------------------------------- in-graph
# fedrec-lint: traced-scope — compiled into the shard_map round-end sync
def _gather_cohort(x: jnp.ndarray, axis: Any) -> jnp.ndarray:
    """All clients' values as a leading (n, ...) dim, regardless of the
    client->chip packing. Cohort deployments sync over a (LOCAL_AXIS,
    mesh_axis) tuple — ``all_gather`` does not take the joint tuple under
    vmap, so gather one axis at a time and flatten; values and weights go
    through the SAME function, so their per-client pairing is consistent
    (the aggregators treat clients symmetrically, so the flattened order
    itself does not matter)."""
    if isinstance(axis, (tuple, list)):
        out = x
        for ax in axis:
            out = lax.all_gather(out, axis_name=ax, axis=0)
        return out.reshape((-1,) + tuple(x.shape))
    return lax.all_gather(x, axis_name=axis, axis=0)


# fedrec-lint: traced-scope — compiled into the shard_map round-end sync
def _sorted_participants(gathered: jnp.ndarray, wmask: jnp.ndarray):
    """Sort a gathered (n, ...) leaf so finite participant values come
    first, ascending; everything else (dropouts, quarantined clients,
    NaN/inf cells) is replaced by +inf and lands at the end. Returns
    ``(sorted_vals, m)`` where ``m`` is the per-coordinate count of finite
    participant values."""
    shape = (-1,) + (1,) * (gathered.ndim - 1)
    w = wmask.reshape(shape)
    finite = jnp.isfinite(gathered) & (w > 0)
    vals = jnp.where(finite, gathered, jnp.inf)
    return jnp.sort(vals, axis=0), jnp.sum(finite.astype(jnp.int32), axis=0)


# fedrec-lint: traced-scope — compiled into the shard_map round-end sync
def _trimmed_mean_leaf(gathered, wmask, trim_k: int):
    srt, m = _sorted_participants(gathered, wmask)
    pos = jnp.arange(srt.shape[0]).reshape((-1,) + (1,) * (srt.ndim - 1))
    # clamp so >= 1 value is always kept, even per-coordinate
    k = jnp.minimum(trim_k, (m - 1) // 2)
    keep = (pos >= k) & (pos < m - k)
    denom = jnp.maximum(m - 2 * k, 1).astype(srt.dtype)
    mean = jnp.sum(jnp.where(keep, srt, 0.0), axis=0) / denom
    return mean, m


# fedrec-lint: traced-scope — compiled into the shard_map round-end sync
def _median_leaf(gathered, wmask):
    srt, m = _sorted_participants(gathered, wmask)
    pos = jnp.arange(srt.shape[0]).reshape((-1,) + (1,) * (srt.ndim - 1))
    lo, hi = (m - 1) // 2, m // 2  # equal when m is odd
    safe = jnp.where(jnp.isfinite(srt), srt, 0.0)  # m==0: all-inf column
    lo_v = jnp.sum(jnp.where(pos == lo, safe, 0.0), axis=0)
    hi_v = jnp.sum(jnp.where(pos == hi, safe, 0.0), axis=0)
    return 0.5 * (lo_v + hi_v), m


# fedrec-lint: traced-scope — compiled into the shard_map round-end sync
def robust_aggregate(
    trees: Any,
    weight: jnp.ndarray,
    axis: Any,
    method: str,
    trim_k: int = 1,
    clip_norm: float = 10.0,
) -> Any:
    """Robust round-end aggregation inside ``shard_map``.

    ``trees`` is any pytree of per-client parameter leaves (pass BOTH
    towers as one tuple so the ``clip`` method's global norm spans the
    whole client update); ``weight`` is this client's scalar round weight
    (0 = dropped out / quarantined). Every client — including
    non-participants — adopts the aggregate, mirroring
    :func:`fedrec_tpu.fed.strategies.weighted_param_avg`; a round where no
    client reports keeps local parameters.
    """
    validate_robust_method(method)
    if method == "mean":
        from fedrec_tpu.fed.strategies import weighted_param_avg

        return weighted_param_avg(trees, weight, axis)

    gw = _gather_cohort(weight, axis)  # (n,)
    wmask = (gw > 0).astype(jnp.float32)
    gathered = jax.tree_util.tree_map(lambda p: _gather_cohort(p, axis), trees)
    any_participant = jnp.sum(wmask) > 0

    if method in ("trimmed_mean", "median"):

        def agg_leaf(local, g):
            if method == "trimmed_mean":
                agg, m = _trimmed_mean_leaf(g, wmask, trim_k)
            else:
                agg, m = _median_leaf(g, wmask)
            # per-coordinate m==0 (every contribution non-finite) and the
            # zero-participation round both keep the local value
            return jnp.where(any_participant & (m > 0), agg.astype(local.dtype),
                             local)

        return jax.tree_util.tree_map(agg_leaf, trees, gathered)

    # ---- method == "clip": centered (at the cohort median) clipped mean.
    centers = jax.tree_util.tree_map(
        lambda g: _median_leaf(g, wmask)[0], gathered
    )
    # per-client squared deviation from the center, global over ALL leaves
    n = gw.shape[0]
    sq = jnp.zeros((n,), jnp.float32)
    for g, c in zip(
        jax.tree_util.tree_leaves(gathered), jax.tree_util.tree_leaves(centers)
    ):
        d = g.astype(jnp.float32) - c.astype(jnp.float32)[None]
        # non-finite deviations poison the norm ON PURPOSE: the client's
        # whole contribution then clips to zero below
        sq = sq + jnp.sum(d.reshape(n, -1) ** 2, axis=1)
    norm = jnp.sqrt(sq)
    scale = jnp.where(
        jnp.isfinite(norm),
        jnp.minimum(1.0, clip_norm / jnp.maximum(norm, 1e-12)),
        0.0,
    )
    total = jnp.sum(gw * wmask)
    coeff = gw * wmask * scale  # (n,)

    def clip_leaf(local, g, c):
        d = g - c[None]
        safe_d = jnp.where(jnp.isfinite(d), d, 0.0)
        numer = jnp.tensordot(coeff.astype(g.dtype), safe_d, axes=(0, 0))
        agg = c + numer / jnp.maximum(total, 1e-12).astype(g.dtype)
        return jnp.where(any_participant, agg, local)

    return jax.tree_util.tree_map(clip_leaf, trees, gathered, centers)


# ----------------------------------------------------------------- numpy
def robust_reduce_np(
    stacked: np.ndarray,
    weights: np.ndarray,
    method: str,
    trim_k: int = 1,
    clip_norm: float = 10.0,
    sq_norms: np.ndarray | None = None,
    fallback: np.ndarray | None = None,
) -> np.ndarray:
    """Numpy robust reduction over a (P, ...) stack of per-process
    contributions — the coordinator deployment's cross-host counterpart of
    :func:`robust_aggregate`, applied to the arrays
    ``multihost_utils.process_allgather`` already materializes.

    Semantics match the in-graph version per leaf: participation =
    ``weights > 0``, non-finite cells excluded, trimming/median per
    coordinate — including the m==0 coordinate (every contribution
    non-finite), which keeps the ``fallback`` value (the caller's local
    params, mirroring the in-graph ``m > 0`` guard; 0.0 when no fallback
    is given). ``clip`` needs the per-process GLOBAL deviation norm
    across every leaf — pass the summed squared deviations via
    ``sq_norms`` (see :func:`robust_reduce_tree_np`), else the leaf is
    clipped by its own norm.
    """
    validate_robust_method(method)
    w = np.asarray(weights, np.float64)
    x = np.asarray(stacked, np.float64)
    wmask = (w > 0).reshape((-1,) + (1,) * (x.ndim - 1))
    if method == "mean":
        total = float(np.sum(w))
        if total == 0:
            raise ValueError("mean reduction needs >= 1 participant")
        contrib = np.where(wmask > 0, x, 0.0)
        return np.einsum("p,p...->...", w, contrib) / total

    finite = np.isfinite(x) & (wmask > 0)
    vals = np.where(finite, x, np.inf)
    srt = np.sort(vals, axis=0)
    m = finite.sum(axis=0)
    pos = np.arange(x.shape[0]).reshape((-1,) + (1,) * (x.ndim - 1))
    fb = 0.0 if fallback is None else np.asarray(fallback, np.float64)
    if method == "trimmed_mean":
        k = np.minimum(trim_k, (m - 1) // 2)
        keep = (pos >= k) & (pos < m - k)
        denom = np.maximum(m - 2 * k, 1)
        out = np.where(keep, np.where(np.isfinite(srt), srt, 0.0), 0.0).sum(0)
        return np.where(m > 0, out / denom, fb)
    if method == "median":
        lo, hi = (m - 1) // 2, m // 2
        safe = np.where(np.isfinite(srt), srt, 0.0)
        lo_v = np.where(pos == lo, safe, 0.0).sum(0)
        hi_v = np.where(pos == hi, safe, 0.0).sum(0)
        return np.where(m > 0, 0.5 * (lo_v + hi_v), fb)

    # clip
    lo, hi = (m - 1) // 2, m // 2
    safe = np.where(np.isfinite(srt), srt, 0.0)
    center = 0.5 * (
        np.where(pos == lo, safe, 0.0).sum(0) + np.where(pos == hi, safe, 0.0).sum(0)
    )
    d = x - center[None]
    if sq_norms is None:
        d_flat = d.reshape(x.shape[0], -1)
        finite_rows = np.isfinite(d_flat).all(axis=1)
        sq_norms = np.where(
            finite_rows,
            (np.where(np.isfinite(d_flat), d_flat, 0.0) ** 2).sum(axis=1),
            np.inf,
        )
    norm = np.sqrt(sq_norms)
    scale = np.where(
        np.isfinite(norm), np.minimum(1.0, clip_norm / np.maximum(norm, 1e-12)), 0.0
    )
    coeff = w * (w > 0) * scale
    total = float(np.sum(w * (w > 0)))
    if total == 0:
        raise ValueError("clip reduction needs >= 1 participant")
    safe_d = np.where(np.isfinite(d), d, 0.0)
    return center + np.einsum("p,p...->...", coeff, safe_d) / total


def robust_reduce_tree_np(
    gathered_tree: Any,
    weights: np.ndarray,
    method: str,
    trim_k: int = 1,
    clip_norm: float = 10.0,
    fallback_tree: Any = None,
) -> Any:
    """Tree-wide numpy robust reduction: every leaf is a (P, ...) stack.
    For ``clip`` the per-process deviation norm is computed globally over
    all leaves first (matching the in-graph method), then each leaf is
    reduced with the shared scales. ``fallback_tree`` (the caller's LOCAL
    params, unstacked) supplies the kept value for coordinates where every
    contribution is non-finite — the in-graph ``m > 0`` guard."""
    validate_robust_method(method)
    leaves, treedef = jax.tree_util.tree_flatten(gathered_tree)
    leaves = [np.asarray(leaf, np.float64) for leaf in leaves]
    fb_leaves: list = [None] * len(leaves)
    if fallback_tree is not None:
        fb_leaves = jax.tree_util.tree_flatten(fallback_tree)[0]
    if method != "clip":
        out = [
            robust_reduce_np(leaf, weights, method, trim_k=trim_k, fallback=fb)
            for leaf, fb in zip(leaves, fb_leaves)
        ]
        return jax.tree_util.tree_unflatten(treedef, out)
    # shared per-process squared deviation norm across all leaves
    n = leaves[0].shape[0]
    sq = np.zeros((n,), np.float64)
    for leaf in leaves:
        w = np.asarray(weights, np.float64)
        x = leaf
        wmask = (w > 0).reshape((-1,) + (1,) * (x.ndim - 1))
        finite = np.isfinite(x) & (wmask > 0)
        vals = np.where(finite, x, np.inf)
        srt = np.sort(vals, axis=0)
        m = finite.sum(axis=0)
        pos = np.arange(n).reshape((-1,) + (1,) * (x.ndim - 1))
        lo, hi = (m - 1) // 2, m // 2
        safe = np.where(np.isfinite(srt), srt, 0.0)
        center = 0.5 * (
            np.where(pos == lo, safe, 0.0).sum(0)
            + np.where(pos == hi, safe, 0.0).sum(0)
        )
        d = (x - center[None]).reshape(n, -1)
        finite_rows = np.isfinite(d).all(axis=1)
        sq_leaf = np.where(np.isfinite(d), d, 0.0) ** 2
        sq = sq + np.where(finite_rows, sq_leaf.sum(axis=1), np.inf)
    out = [
        robust_reduce_np(
            leaf, weights, "clip", clip_norm=clip_norm, sq_norms=sq
        )
        for leaf in leaves
    ]
    return jax.tree_util.tree_unflatten(treedef, out)
