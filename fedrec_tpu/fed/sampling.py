"""Seeded, checkpointable per-round cohort sampling over a logical-client
population.

Cross-device federation (FedJAX, arxiv 2108.02117; "Scaling Federated
Learning for Fine-tuning of Large Language Models") trains a population of
N >> devices logical clients by sampling a cohort per round. The sampler
here is the ONE source of the cohort schedule:

* **deterministic** — a draw is a pure function of ``(seed, round_idx,
  attempt)`` plus the sampler's fairness state, so two runs with the same
  seed produce the identical schedule, and a quorum re-draw (``attempt``
  bumps) is itself reproducible;
* **checkpointable** — :meth:`CohortSampler.state_dict` /
  :meth:`load_state_dict` round-trip the mutable state (the skew mode's
  selection counts, the committed-round counter), so a restored run
  resumes the *identical* cohort schedule (pinned in
  ``tests/test_population.py``);
* **priority-ordered** — the returned ids are in descending draw priority:
  the cohort packer fills device slots front-to-back, so over-selected
  spares are exactly the tail of the draw.

Modes (``fed.population.sampler``):

* ``uniform``  — every eligible client equally likely (Gumbel-top-k over
  zero log-weights == a uniform sample without replacement);
* ``weighted`` — probability proportional to the client's sample count
  (classic cross-device selection bias toward data-rich clients);
* ``skew``     — non-IID-skew-aware coverage sampling: log-weight
  ``-log1p(times_selected)``, so rarely-seen clients are favored and the
  population's selection histogram flattens over rounds — the antidote to
  uniform sampling starving the tail under heavy-tailed availability.

The degenerate contract: when ``k`` covers the whole eligible population
the draw returns the eligible ids in ASCENDING ID ORDER (not priority
order), so a population == slots configuration packs client *i* into slot
*i* every round and the trainer's load/unload machinery is a no-op — the
bit-identical cross-silo limit.
"""

from __future__ import annotations

import numpy as np

SAMPLER_MODES = ("uniform", "weighted", "skew")


def validate_sampler_mode(mode: str) -> str:
    if mode not in SAMPLER_MODES:
        raise ValueError(
            f"unknown fed.population.sampler {mode!r}; expected one of "
            f"{SAMPLER_MODES}"
        )
    return mode


class CohortSampler:
    """Per-round cohort draws over ``population`` logical clients."""

    def __init__(
        self,
        population: int,
        mode: str = "uniform",
        seed: int = 0,
        sample_counts: np.ndarray | None = None,
        skew_strength: float = 1.0,
    ):
        if population <= 0:
            raise ValueError(f"population must be positive, got {population}")
        validate_sampler_mode(mode)
        self.population = int(population)
        self.mode = mode
        self.seed = int(seed)
        self.skew_strength = float(skew_strength)
        if sample_counts is None:
            sample_counts = np.ones((self.population,), np.int64)
        sample_counts = np.asarray(sample_counts, np.int64)
        if sample_counts.shape != (self.population,):
            raise ValueError(
                f"sample_counts shape {sample_counts.shape} != "
                f"({self.population},)"
            )
        if mode == "weighted" and not (sample_counts > 0).any():
            raise ValueError("weighted sampling needs >= 1 positive count")
        self.sample_counts = sample_counts
        # mutable fairness state — the checkpointed part
        self.selection_counts = np.zeros((self.population,), np.int64)
        self.rounds_committed = 0

    # ---------------------------------------------------------------- draw
    def _log_weights(self) -> np.ndarray:
        if self.mode == "uniform":
            return np.zeros((self.population,), np.float64)
        if self.mode == "weighted":
            return np.log(np.maximum(self.sample_counts, 1).astype(np.float64))
        # skew: favor clients the schedule has seen least
        return -self.skew_strength * np.log1p(
            self.selection_counts.astype(np.float64)
        )

    def draw(
        self,
        round_idx: int,
        k: int,
        exclude: set | frozenset | tuple = (),
        attempt: int = 0,
    ) -> np.ndarray:
        """``min(k, eligible)`` distinct client ids for one round.

        Pure in ``(seed, round_idx, attempt)`` and the current fairness
        state; does NOT mutate state — call :meth:`record` once the round
        the cohort trained actually commits (so a rolled-back round does
        not skew the coverage counts).
        """
        eligible = np.ones((self.population,), bool)
        for c in exclude:
            if 0 <= int(c) < self.population:
                eligible[int(c)] = False
        n_eligible = int(eligible.sum())
        if n_eligible == 0:
            return np.zeros((0,), np.int64)
        ids = np.nonzero(eligible)[0]
        if k >= n_eligible:
            # degenerate contract: full coverage keeps ascending id order,
            # so population == slots packs identity and swaps nothing
            return ids.astype(np.int64)
        rng = np.random.default_rng(
            [self.seed, int(round_idx), int(attempt), 0xC0407]
        )
        # Gumbel-top-k == sampling without replacement proportional to the
        # (exp of the) log-weights; one vectorized draw, no rejection loop
        keys = self._log_weights() + rng.gumbel(size=self.population)
        keys[~eligible] = -np.inf
        order = np.argsort(-keys, kind="stable")
        return order[:k].astype(np.int64)

    def record(self, cohort: np.ndarray) -> None:
        """Commit one round's cohort into the fairness state."""
        cohort = np.asarray(cohort, np.int64)
        if cohort.size:
            np.add.at(self.selection_counts, cohort, 1)
        self.rounds_committed += 1

    # --------------------------------------------------------- persistence
    def state_dict(self) -> dict:
        return {
            "population": np.int64(self.population),
            "mode": self.mode,
            "seed": np.int64(self.seed),
            "selection_counts": self.selection_counts.copy(),
            "rounds_committed": np.int64(self.rounds_committed),
        }

    def load_state_dict(self, state: dict) -> None:
        pop = int(state["population"])
        mode = str(state["mode"])
        if pop != self.population or mode != self.mode:
            raise ValueError(
                f"sampler state mismatch: saved (population={pop}, "
                f"mode={mode!r}) vs configured "
                f"(population={self.population}, mode={self.mode!r}) — the "
                "snapshot was written under a different fed.population "
                "config"
            )
        if int(state["seed"]) != self.seed:
            print(
                "[sampling] WARNING: restored sampler seed "
                f"{int(state['seed'])} != configured {self.seed}; the "
                "resumed schedule follows the CONFIGURED seed"
            )
        counts = np.asarray(state["selection_counts"], np.int64)
        if counts.shape != (self.population,):
            raise ValueError(
                f"restored selection_counts shape {counts.shape} != "
                f"({self.population},)"
            )
        self.selection_counts = counts.copy()
        self.rounds_committed = int(state["rounds_committed"])
