"""Attention modules (Flax linen), TPU-first re-designs of the reference's
``attention.py``.

Behavioral parity targets:
  * ``AdditiveAttention`` — learned-query pooling ``fc(dh->hidden) -> tanh ->
    fc(->1) -> normalize -> weighted sum`` (reference ``attention.py:8-26``).
  * ``MultiHeadAttention`` — Q/K/V projections, scaled dot-product, **no
    output projection** (reference ``attention.py:50-82``), Xavier-uniform
    kernel init (reference ``attention.py:64-67``).

Numerics divergence (ledger): the reference normalizes attention with a raw
``exp`` (no max subtraction — ``attention.py:19,39``), which overflows for
moderate logits. We default to a numerically-stable softmax and keep
``stable_softmax=False`` for bit-parity experiments; with a mask both forms
share the reference's ``alpha * mask / (sum + 1e-8)`` masking semantics.

All shapes are batched leading dims + ``(seq, feature)`` trailing; everything
lives inside one jit region so XLA fuses the pipelines into the MXU matmuls.
"""

from __future__ import annotations

import jax.numpy as jnp
from flax import linen as nn


def _masked_normalize(
    logits: jnp.ndarray, mask: jnp.ndarray | None, axis: int, stable: bool
) -> jnp.ndarray:
    """Reference-style exp-normalization, optionally max-stabilized.

    ``exp(logits) * mask / (sum + 1e-8)`` — with ``stable=True`` the logits
    are shifted by their max first, which changes nothing mathematically
    (modulo the epsilon) but cannot overflow.
    """
    if stable:
        logits = logits - jnp.max(logits, axis=axis, keepdims=True)
    weights = jnp.exp(logits)
    if mask is not None:
        weights = weights * mask
    return weights / (jnp.sum(weights, axis=axis, keepdims=True) + 1e-8)


class AdditiveAttention(nn.Module):
    """Learned-query additive pooling over a sequence: (..., L, D) -> (..., D).

    ``use_pallas=True`` routes through the fused VMEM kernel
    (``fedrec_tpu.ops.additive_pool``); requires ``stable_softmax`` (the
    kernel computes a true softmax — the fc2 bias, a softmax-invariant
    constant shift, is omitted there; its gradient is exactly zero either
    way). Falls back to the jnp path otherwise.
    """

    hidden: int = 200
    stable_softmax: bool = True
    dtype: jnp.dtype = jnp.float32
    use_pallas: bool = False
    seq_axis: str | None = None  # sequence-parallel mesh axis (inside shard_map)

    @nn.compact
    def __call__(
        self, x: jnp.ndarray, mask: jnp.ndarray | None = None
    ) -> jnp.ndarray:
        fc1 = nn.Dense(self.hidden, dtype=self.dtype, name="att_fc1")
        fc2 = nn.Dense(1, dtype=self.dtype, name="att_fc2")
        if self.seq_axis is not None:
            # x holds only this chip's sequence shard; normalize globally
            from fedrec_tpu.parallel.ring import seq_parallel_pool

            logits = fc2(jnp.tanh(fc1(x)))[..., 0]
            if mask is not None:
                mask = mask.astype(logits.dtype)
            return seq_parallel_pool(x, logits, mask, self.seq_axis)
        if self.use_pallas and self.stable_softmax:
            from fedrec_tpu.ops import additive_pool

            # zero-length calls create the (identical) param tree; XLA DCEs them
            fc2(fc1(x[..., :0, :]))
            p1, p2 = fc1.variables["params"], fc2.variables["params"]
            return additive_pool(
                x, p1["kernel"], p1["bias"], p2["kernel"][:, 0], mask
            )
        e = jnp.tanh(fc1(x))
        logits = fc2(e)[..., 0]  # (..., L)
        if mask is not None:
            mask = mask.astype(logits.dtype)
        alpha = _masked_normalize(logits, mask, axis=-1, stable=self.stable_softmax)
        return jnp.einsum("...l,...ld->...d", alpha, x)


class MultiHeadAttention(nn.Module):
    """Multi-head scaled-dot-product attention WITHOUT output projection.

    The reference concatenates per-head contexts and returns them directly
    (``attention.py:81``); head mixing happens only implicitly in downstream
    layers. Kernel init is Xavier-uniform to match ``attention.py:64-67``
    (biases zero-init — the reference leaves torch's default bias init in
    place, a divergence recorded in the ledger).
    """

    num_heads: int = 20
    head_dim: int = 20
    stable_softmax: bool = True
    dtype: jnp.dtype = jnp.float32
    use_pallas: bool = False
    seq_axis: str | None = None  # sequence-parallel mesh axis (inside shard_map)
    seq_impl: str = "ring"  # "ring" | "ulysses"
    # "auto" | "dense" | "chunked" | "pallas" — see ModelConfig.attn_impl
    attn_impl: str = "auto"
    chunk_threshold: int = 1024

    @nn.compact
    def __call__(
        self,
        q: jnp.ndarray,
        k: jnp.ndarray,
        v: jnp.ndarray,
        mask: jnp.ndarray | None = None,
    ) -> jnp.ndarray:
        d = self.num_heads * self.head_dim
        dense = lambda name: nn.Dense(  # noqa: E731
            d,
            dtype=self.dtype,
            kernel_init=nn.initializers.xavier_uniform(),
            name=name,
        )
        *batch, L, _ = q.shape

        def split_heads(x):
            return x.reshape(*batch, -1, self.num_heads, self.head_dim)

        q_s = split_heads(dense("w_q")(q))  # (..., L, H, Dk)
        k_s = split_heads(dense("w_k")(k))
        v_s = split_heads(dense("w_v")(v))

        if self.seq_axis is not None:
            # sequence-sharded long-context path; L here is this chip's shard
            from fedrec_tpu.parallel.ring import ring_attention, ulysses_attention

            if self.seq_impl not in ("ring", "ulysses"):
                raise ValueError(
                    f"seq_impl must be 'ring' or 'ulysses', got {self.seq_impl!r}"
                )
            sp = ring_attention if self.seq_impl == "ring" else ulysses_attention
            context = sp(q_s, k_s, v_s, mask, self.seq_axis)
            return context.reshape(*batch, L, d)

        impl = self.attn_impl
        if impl == "auto":
            if self.use_pallas and self.stable_softmax:
                # explicit kernel opt-in still outranks banked evidence
                impl = "pallas"
            else:
                # evidence-driven: the measured winner for this (H, dtype)
                # regime from a provenance-clean pallas_bench artifact on a
                # live TPU backend; None (no applicable clean evidence, or
                # off-TPU) falls back to the static defaults below
                from fedrec_tpu.ops.autotune import measured_attn_impl

                measured = measured_attn_impl(L, jnp.dtype(self.dtype))
                if measured is not None and (
                    measured == "dense" or self.stable_softmax
                ):
                    impl = measured
                elif L > self.chunk_threshold and self.stable_softmax:
                    impl = "chunked"
                else:
                    impl = "dense"
        if impl == "pallas":
            # blocked online-softmax kernel: no (..., H, L, L) score tensor
            from fedrec_tpu.ops import flash_attention

            context = flash_attention(q_s, k_s, v_s, mask)
            return context.reshape(*batch, L, d)
        if impl == "chunked":
            # blockwise lax.scan, O(L) memory — the single-chip long-context
            # path (chunked_attention docstring has the measured rationale)
            from fedrec_tpu.ops import chunked_attention

            context = chunked_attention(q_s, k_s, v_s, mask)
            return context.reshape(*batch, L, d)
        if impl != "dense":
            raise ValueError(
                f"attn_impl must be auto|dense|chunked|pallas, got {impl!r}"
            )

        scores = jnp.einsum("...qhd,...khd->...hqk", q_s, k_s) / jnp.sqrt(
            jnp.asarray(self.head_dim, dtype=q_s.dtype)
        )
        if mask is not None:
            # (..., Lk) key mask broadcast over heads and query positions
            mask = mask[..., None, None, :].astype(scores.dtype)
        attn = _masked_normalize(scores, mask, axis=-1, stable=self.stable_softmax)
        context = jnp.einsum("...hqk,...khd->...qhd", attn, v_s)
        return context.reshape(*batch, L, d)
