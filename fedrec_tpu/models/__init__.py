from fedrec_tpu.models.attention import AdditiveAttention, MultiHeadAttention
from fedrec_tpu.models.encoders import TextHead, UserEncoder
from fedrec_tpu.models.recommender import NewsRecommender, score_candidates, score_loss

__all__ = [
    "AdditiveAttention",
    "MultiHeadAttention",
    "NewsRecommender",
    "TextHead",
    "UserEncoder",
    "score_candidates",
    "score_loss",
]
