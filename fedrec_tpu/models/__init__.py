from fedrec_tpu.models.attention import AdditiveAttention, MultiHeadAttention
from fedrec_tpu.models.bert import (
    DistilBert,
    DistilBertConfig,
    TextEncoder,
    convert_hf_state_dict,
    init_trunk_params,
    load_hf_state_dict,
    precompute_token_states,
)
from fedrec_tpu.models.encoders import (
    CnnTextHead,
    GRUUserEncoder,
    TextHead,
    UserEncoder,
)
from fedrec_tpu.models.recommender import NewsRecommender, score_candidates, score_loss

__all__ = [
    "AdditiveAttention",
    "DistilBert",
    "DistilBertConfig",
    "MultiHeadAttention",
    "NewsRecommender",
    "TextEncoder",
    "TextHead",
    "CnnTextHead",
    "GRUUserEncoder",
    "UserEncoder",
    "convert_hf_state_dict",
    "init_trunk_params",
    "load_hf_state_dict",
    "precompute_token_states",
    "score_candidates",
    "score_loss",
]
