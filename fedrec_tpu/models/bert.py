"""Flax DistilBERT trunk — the frozen text-encoder backbone.

The reference wraps HuggingFace's torch ``DistilBertModel`` (reference
``encoder.py:19``: ``DistilBertModel.from_pretrained('distilbert-base-uncased')``)
and freezes it (``model.py:25-26``), re-running it on every news title every
batch (the dominant cost, reference ``model.py:41-61``). The TPU design
instead:

  * implements DistilBERT natively in Flax (this module) so the trunk is one
    jittable XLA program — big batched matmuls on the MXU, bfloat16-capable;
  * precomputes the per-news token states ONCE (``precompute_token_states``)
    and caches them HBM-/host-resident; only the small trainable head runs in
    the hot loop (see ``fedrec_tpu.models.encoders.TextHead``);
  * supports full in-loop fine-tuning (``text_encoder_mode='finetune'``,
    BASELINE config 5) via ``TextEncoder`` with ``jax.checkpoint`` remat.

Pretrained weights are loaded by converting a HuggingFace torch ``state_dict``
(``load_hf_state_dict``) — no network access required; point it at a local
``pytorch_model.bin`` / ``model.safetensors``. Without weights the trunk
random-initializes (useful for smoke tests and from-scratch runs).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Mapping

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn


@dataclass(frozen=True)
class DistilBertConfig:
    """Architecture knobs; defaults = ``distilbert-base-uncased``."""

    vocab_size: int = 30522
    max_position_embeddings: int = 512
    dim: int = 768
    n_layers: int = 6
    n_heads: int = 12
    hidden_dim: int = 3072          # FFN inner dim
    dropout: float = 0.1
    attention_dropout: float = 0.1
    layer_norm_eps: float = 1e-12


class _SelfAttention(nn.Module):
    """Standard post-LN transformer self-attention WITH output projection.

    (Unlike the recommender's ``MultiHeadAttention``, which follows the
    reference user encoder's no-output-projection design,
    reference ``attention.py:81`` — DistilBERT has ``out_lin``.)
    """

    cfg: DistilBertConfig
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(
        self, x: jnp.ndarray, mask: jnp.ndarray, train: bool = False
    ) -> jnp.ndarray:
        c = self.cfg
        head_dim = c.dim // c.n_heads
        dense = lambda name: nn.Dense(c.dim, dtype=self.dtype, name=name)  # noqa: E731
        b, L, _ = x.shape

        def split(t):
            return t.reshape(b, L, c.n_heads, head_dim)

        q = split(dense("q_lin")(x)) / jnp.sqrt(jnp.asarray(head_dim, self.dtype))
        k = split(dense("k_lin")(x))
        v = split(dense("v_lin")(x))
        scores = jnp.einsum("bqhd,bkhd->bhqk", q, k)
        # (b, L) key mask -> additive bias; padded keys get -inf-ish
        bias = jnp.where(mask[:, None, None, :] > 0, 0.0, -1e9).astype(scores.dtype)
        attn = jax.nn.softmax(scores + bias, axis=-1)
        attn = nn.Dropout(c.attention_dropout, deterministic=not train)(attn)
        ctx = jnp.einsum("bhqk,bkhd->bqhd", attn, v).reshape(b, L, c.dim)
        return nn.Dense(c.dim, dtype=self.dtype, name="out_lin")(ctx)


class _TransformerBlock(nn.Module):
    cfg: DistilBertConfig
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(
        self, x: jnp.ndarray, mask: jnp.ndarray, train: bool = False
    ) -> jnp.ndarray:
        c = self.cfg
        attn_out = _SelfAttention(c, self.dtype, name="attention")(x, mask, train)
        x = nn.LayerNorm(epsilon=c.layer_norm_eps, dtype=self.dtype, name="sa_layer_norm")(
            x + attn_out
        )
        h = nn.Dense(c.hidden_dim, dtype=self.dtype, name="lin1")(x)
        h = nn.gelu(h, approximate=False)
        h = nn.Dense(c.dim, dtype=self.dtype, name="lin2")(h)
        h = nn.Dropout(c.dropout, deterministic=not train)(h)
        return nn.LayerNorm(
            epsilon=c.layer_norm_eps, dtype=self.dtype, name="output_layer_norm"
        )(x + h)


class DistilBert(nn.Module):
    """Token ids + attention mask -> per-token hidden states (B, L, dim)."""

    cfg: DistilBertConfig = DistilBertConfig()
    dtype: jnp.dtype = jnp.float32
    remat: bool = False               # jax.checkpoint each block (finetune mode)

    @nn.compact
    def __call__(
        self,
        input_ids: jnp.ndarray,       # (B, L) int
        attention_mask: jnp.ndarray,  # (B, L) 0/1
        train: bool = False,
    ) -> jnp.ndarray:
        c = self.cfg
        positions = jnp.arange(input_ids.shape[1])[None, :]
        x = nn.Embed(c.vocab_size, c.dim, dtype=self.dtype, name="word_embeddings")(
            input_ids
        )
        x = x + nn.Embed(
            c.max_position_embeddings, c.dim, dtype=self.dtype,
            name="position_embeddings",
        )(positions)
        x = nn.LayerNorm(epsilon=c.layer_norm_eps, dtype=self.dtype, name="emb_layer_norm")(x)
        x = nn.Dropout(c.dropout, deterministic=not train)(x)
        block_cls = _TransformerBlock
        if self.remat:
            block_cls = nn.remat(_TransformerBlock, static_argnums=(3,))
        for i in range(c.n_layers):
            x = block_cls(c, self.dtype, name=f"layer_{i}")(x, attention_mask, train)
        return x


# --------------------------------------------------------- weight conversion
def convert_hf_state_dict(
    state_dict: Mapping[str, Any], cfg: DistilBertConfig
) -> dict:
    """HF torch ``DistilBertModel`` state_dict -> Flax ``DistilBert`` params.

    Accepts tensors or numpy arrays; keys may carry a ``distilbert.`` prefix
    (full-model checkpoints). Dense kernels are transposed (torch stores
    ``(out, in)``; Flax expects ``(in, out)``).
    """

    def arr(key: str) -> np.ndarray:
        for k in (key, f"distilbert.{key}"):
            if k in state_dict:
                v = state_dict[k]
                return np.asarray(v.detach().cpu().numpy() if hasattr(v, "detach") else v)
        raise KeyError(f"missing key {key!r} in state_dict")

    def dense(key: str) -> dict:
        return {"kernel": arr(f"{key}.weight").T, "bias": arr(f"{key}.bias")}

    def ln(key: str) -> dict:
        return {"scale": arr(f"{key}.weight"), "bias": arr(f"{key}.bias")}

    params: dict = {
        "word_embeddings": {"embedding": arr("embeddings.word_embeddings.weight")},
        "position_embeddings": {
            "embedding": arr("embeddings.position_embeddings.weight")
        },
        "emb_layer_norm": ln("embeddings.LayerNorm"),
    }
    for i in range(cfg.n_layers):
        p = f"transformer.layer.{i}"
        params[f"layer_{i}"] = {
            "attention": {
                "q_lin": dense(f"{p}.attention.q_lin"),
                "k_lin": dense(f"{p}.attention.k_lin"),
                "v_lin": dense(f"{p}.attention.v_lin"),
                "out_lin": dense(f"{p}.attention.out_lin"),
            },
            "sa_layer_norm": ln(f"{p}.sa_layer_norm"),
            "lin1": dense(f"{p}.ffn.lin1"),
            "lin2": dense(f"{p}.ffn.lin2"),
            "output_layer_norm": ln(f"{p}.output_layer_norm"),
        }
    return jax.tree_util.tree_map(jnp.asarray, params)


def load_hf_state_dict(path: str, cfg: DistilBertConfig | None = None) -> dict:
    """Load a local HF checkpoint file (.bin via torch, .safetensors) and
    convert. Works fully offline; raises with a clear message if the needed
    loader is unavailable."""
    cfg = cfg or DistilBertConfig()
    if str(path).endswith(".safetensors"):
        from safetensors.numpy import load_file  # ships with transformers deps

        sd = load_file(path)
    else:
        import torch

        sd = torch.load(path, map_location="cpu", weights_only=True)
    return convert_hf_state_dict(sd, cfg)


# --------------------------------------------------------- trunk precompute
def precompute_token_states(
    params: dict,
    news_tokens: np.ndarray,
    cfg: DistilBertConfig | None = None,
    chunk: int = 256,
    dtype: str = "float32",
) -> np.ndarray:
    """(N_news, 2, L) artifact -> (N_news, L, dim) frozen-trunk token states.

    The once-per-corpus replacement for the reference re-running DistilBERT
    per news per batch (``model.py:41-61``). Chunked, jitted; returns numpy
    (host-resident — the Trainer moves it to HBM).
    """
    cfg = cfg or DistilBertConfig()
    model = DistilBert(cfg, dtype=jnp.dtype(dtype))
    n = news_tokens.shape[0]
    chunk = min(chunk, n)

    # params as a jit ARGUMENT (not a closure constant): closing over would
    # bake ~66M weights into the jaxpr as constants for the real trunk
    @jax.jit
    def run(p, ids, mask):
        return model.apply({"params": p}, ids, mask)

    # preallocate: a chunk-list + concatenate would transiently double the
    # footprint of an already-large array (MIND-large: ~15 GB at float32)
    out = np.empty((n, news_tokens.shape[2], cfg.dim), dtype=dtype)
    for start in range(0, n, chunk):
        block = news_tokens[start : start + chunk]
        ids = jnp.asarray(block[:, 0], jnp.int32)
        mask = jnp.asarray(block[:, 1], jnp.int32)
        pad = chunk - block.shape[0]
        if pad:  # keep shapes static so the last chunk doesn't retrace
            ids = jnp.pad(ids, ((0, pad), (0, 0)))
            mask = jnp.pad(mask, ((0, pad), (0, 0)))
        states = run(params, ids, mask)
        out[start : start + block.shape[0]] = np.asarray(states[: block.shape[0]])
    return out


def init_trunk_params(
    rng: jax.Array, cfg: DistilBertConfig | None = None, title_len: int = 50
) -> dict:
    """Random-init trunk parameters (offline smoke / from-scratch runs)."""
    cfg = cfg or DistilBertConfig()
    model = DistilBert(cfg)
    dummy_ids = jnp.zeros((1, title_len), jnp.int32)
    dummy_mask = jnp.ones((1, title_len), jnp.int32)
    return model.init(rng, dummy_ids, dummy_mask)["params"]


def trunk_config_from(model_cfg) -> DistilBertConfig:
    """DistilBertConfig from a ``ModelConfig`` (finetune-mode trunk knobs)."""
    return DistilBertConfig(
        vocab_size=model_cfg.trunk_vocab,
        dim=model_cfg.bert_hidden,
        n_layers=model_cfg.trunk_layers,
        n_heads=model_cfg.trunk_heads,
        hidden_dim=model_cfg.trunk_ffn,
        dropout=model_cfg.trunk_dropout,
        attention_dropout=model_cfg.trunk_dropout,
    )


def make_text_encoder(model_cfg) -> "TextEncoder":
    """Full trainable text tower for ``text_encoder_mode='finetune'``."""
    if getattr(model_cfg, "text_head_arch", "additive") != "additive":
        raise NotImplementedError(
            "text_encoder_mode='finetune' supports only the additive head; "
            "use text_head_arch='cnn' with mode 'head' or 'table'"
        )
    return TextEncoder(
        trunk_cfg=trunk_config_from(model_cfg),
        news_dim=model_cfg.news_dim,
        stable_softmax=model_cfg.stable_softmax,
        dtype=jnp.dtype(model_cfg.dtype),
        remat=model_cfg.trunk_remat,
    )


class TextEncoder(nn.Module):
    """Full text tower: DistilBERT trunk + additive-attention head.

    The in-loop fine-tuning path (``text_encoder_mode='finetune'``,
    BASELINE config 5). ``remat=True`` rematerializes each transformer block
    on backward, trading FLOPs for HBM. Mirrors reference ``encoder.py:12-30``
    (trunk -> AdditiveAttention(768->384) -> Linear(768->400)) but as one
    jitted program over batched token ids.
    """

    trunk_cfg: DistilBertConfig = DistilBertConfig()
    news_dim: int = 400
    stable_softmax: bool = True
    dtype: jnp.dtype = jnp.float32
    remat: bool = True

    @nn.compact
    def __call__(
        self, tokens: jnp.ndarray, train: bool = False
    ) -> jnp.ndarray:
        """(..., 2, L) stacked [ids; mask] -> (..., news_dim)."""
        from fedrec_tpu.models.encoders import TextHead

        batch_shape = tokens.shape[:-2]
        flat = tokens.reshape(-1, 2, tokens.shape[-1])
        ids, mask = flat[:, 0].astype(jnp.int32), flat[:, 1].astype(jnp.int32)
        states = DistilBert(
            self.trunk_cfg, dtype=self.dtype, remat=self.remat, name="trunk"
        )(ids, mask, train)
        vecs = TextHead(
            news_dim=self.news_dim,
            bert_hidden=self.trunk_cfg.dim,
            stable_softmax=self.stable_softmax,
            dtype=self.dtype,
            name="head",
        )(states)  # reference passes no token mask to the pooler (encoder.py:28)
        return vecs.reshape(*batch_shape, self.news_dim)
