"""Two-tower news recommender: scoring + loss (reference ``model.py:111-129``).

The reference's ``UserModel.forward`` embeds candidates and history via the
text encoder, runs the user encoder, scores with a batched dot product,
applies sigmoid, and feeds the *sigmoid outputs* to ``nn.CrossEntropyLoss``
(reference ``model.py:121-126`` — CE over probabilities, not logits; an
unusual choice we keep as the default for parity, with
``sigmoid_before_ce=False`` exposing the standard logit CE).

Here the model is a pure Flax module over *news vectors*; where those vectors
come from (precomputed table gather, cached-trunk TextHead, or full DistilBERT
fine-tune) is the caller's choice — see ``fedrec_tpu.train``.
"""

from __future__ import annotations

import jax.numpy as jnp
import optax
from flax import linen as nn

from fedrec_tpu.config import ModelConfig
from fedrec_tpu.models.encoders import (
    CnnTextHead,
    GRUUserEncoder,
    TextHead,
    UserEncoder,
)


def score_candidates(cand_vecs: jnp.ndarray, user_vec: jnp.ndarray) -> jnp.ndarray:
    """Dot-product scoring: (..., C, D) x (..., D) -> (..., C).

    The reference's ``torch.bmm(candidate_vecs, user_vector.unsqueeze(-1))``
    (``model.py:121``) as one einsum; XLA maps it onto the MXU.
    """
    return jnp.einsum("...cd,...d->...c", cand_vecs, user_vec)


def score_loss(
    scores: jnp.ndarray,
    labels: jnp.ndarray,
    sigmoid_before_ce: bool = True,
    reduce: bool = True,
) -> jnp.ndarray:
    """Cross-entropy over impressions (labels are always slot 0).

    ``sigmoid_before_ce=True`` reproduces reference ``model.py:123-126``:
    ``CrossEntropyLoss()(sigmoid(scores), labels)``. ``reduce=False``
    returns the per-impression vector (used by evaluation to trim batch
    padding before averaging).
    """
    # loss math always in f32 (cast BEFORE the sigmoid — a bf16 sigmoid
    # would re-quantize): under a bfloat16 model the softmax/log lose ~3
    # decimal digits, quantizing the loss metric (visibly: a constant
    # 0.65625 across rounds) and coarsening gradients near convergence
    scores = scores.astype(jnp.float32)
    logits = nn.sigmoid(scores) if sigmoid_before_ce else scores
    per_row = optax.softmax_cross_entropy_with_integer_labels(logits, labels)
    return jnp.mean(per_row) if reduce else per_row


class NewsRecommender(nn.Module):
    """User encoder + text head under one parameter tree.

    Methods are exposed separately so the train step can call
    ``encode_news`` on unique news only and reuse vectors across candidate
    and history slots (the TPU answer to the reference re-encoding every
    news per sample, ``model.py:41-61``).
    """

    cfg: ModelConfig
    # sequence-parallel mesh axis for the user tower (set inside shard_map
    # regions only — see fedrec_tpu.parallel.ring); None = dense single-chip.
    # Param trees are identical either way, so clones interoperate freely.
    seq_axis: str | None = None
    seq_impl: str = "ring"

    def setup(self):
        dtype = jnp.dtype(self.cfg.dtype)
        arch = getattr(self.cfg, "text_head_arch", "additive")
        if arch == "cnn":
            # attribute name (hence param-tree path "text_head") is shared
            # across head families, like user_tower; leaves differ, so
            # snapshots are per-family
            self.text_head = CnnTextHead(
                news_dim=self.cfg.news_dim,
                bert_hidden=self.cfg.bert_hidden,
                kernel=getattr(self.cfg, "cnn_kernel", 3),
                stable_softmax=self.cfg.stable_softmax,
                dtype=dtype,
                use_pallas=self.cfg.use_pallas,
            )
        elif arch == "additive":
            self.text_head = TextHead(
                news_dim=self.cfg.news_dim,
                bert_hidden=self.cfg.bert_hidden,
                stable_softmax=self.cfg.stable_softmax,
                dtype=dtype,
                use_pallas=self.cfg.use_pallas,
            )
        else:
            raise ValueError(
                f"unknown model.text_head_arch {arch!r}; have 'additive', 'cnn'"
            )
        tower = getattr(self.cfg, "user_tower", "mha")
        fuse = getattr(self.cfg, "fuse_hot_path", False)
        if fuse:
            if tower != "mha":
                raise ValueError(
                    "model.fuse_hot_path fuses the MHA user tower; "
                    f"user_tower={tower!r} has no fused kernel — unset one"
                )
            if not self.cfg.stable_softmax:
                raise ValueError(
                    "model.fuse_hot_path requires stable_softmax=True (the "
                    "fused kernels compute the max-subtracted form; the "
                    "raw-exp parity mode stays on the dense path)"
                )
            if self.seq_axis is not None:
                raise ValueError(
                    "model.fuse_hot_path cannot run under fed.seq_shards>1 "
                    "(the fused kernel holds the whole history per row); "
                    "use the ring/Ulysses path for sharded histories"
                )
        if tower == "gru":
            if self.seq_axis is not None:
                raise ValueError(
                    "model.user_tower='gru' cannot run under fed.seq_shards>1 "
                    "(sequence parallelism is attention-specific); use the "
                    "'mha' tower for seq-sharded histories"
                )
            # attribute name (hence param-tree path "user_encoder") is shared
            # across families; the leaves differ, so snapshots are per-family
            self.user_encoder = GRUUserEncoder(
                news_dim=self.cfg.news_dim,
                query_dim=self.cfg.query_dim,
                dropout_rate=self.cfg.dropout_rate,
                stable_softmax=self.cfg.stable_softmax,
                dtype=dtype,
                use_pallas=self.cfg.use_pallas,
            )
        elif tower == "mha":
            self.user_encoder = UserEncoder(
                news_dim=self.cfg.news_dim,
                num_heads=self.cfg.num_heads,
                head_dim=self.cfg.head_dim,
                query_dim=self.cfg.query_dim,
                dropout_rate=self.cfg.dropout_rate,
                stable_softmax=self.cfg.stable_softmax,
                dtype=dtype,
                use_pallas=self.cfg.use_pallas,
                fuse=fuse,
                seq_axis=self.seq_axis,
                seq_impl=self.seq_impl,
                attn_impl=self.cfg.attn_impl,
                chunk_threshold=self.cfg.attn_chunk_threshold,
            )
        else:
            raise ValueError(
                f"unknown model.user_tower {tower!r}; have 'mha', 'gru'"
            )

    def encode_news(
        self, token_states: jnp.ndarray, mask: jnp.ndarray | None = None
    ) -> jnp.ndarray:
        return self.text_head(token_states, mask)

    def encode_user(
        self,
        his_vecs: jnp.ndarray,
        mask: jnp.ndarray | None = None,
        train: bool = False,
    ) -> jnp.ndarray:
        return self.user_encoder(his_vecs, mask, train)

    def __call__(
        self,
        cand_vecs: jnp.ndarray,
        his_vecs: jnp.ndarray,
        his_mask: jnp.ndarray | None = None,
        train: bool = False,
    ) -> jnp.ndarray:
        """(..., C, D) candidates + (..., H, D) history -> (..., C) scores."""
        if getattr(self.cfg, "fuse_hot_path", False):
            # one fused kernel runs attention + pool + scoring; the dot
            # with the candidates never leaves VMEM (docs/DESIGN.md §5h)
            _, scores = self.user_encoder(
                his_vecs, his_mask, train, cand_vecs=cand_vecs
            )
            return scores
        user_vec = self.user_encoder(his_vecs, his_mask, train)
        return score_candidates(cand_vecs, user_vec)

    def init_both_towers(
        self,
        token_states: jnp.ndarray,
        cand_vecs: jnp.ndarray,
        his_vecs: jnp.ndarray,
    ) -> jnp.ndarray:
        """Init helper: touches both towers so one ``init`` creates the full
        parameter tree (Flax only materializes params for traced modules)."""
        self.text_head(token_states)
        return self(cand_vecs, his_vecs)
