"""News text head and user encoder (Flax linen).

``TextHead`` is the trainable tail of the reference's ``TextEncoder``
(additive attention over token states + Linear 768->400, reference
``encoder.py:20-29``). The frozen DistilBERT trunk's per-news token states
are constant, so the TPU design computes them once, caches them HBM- or
host-resident, and only the head runs in the training step — numerically
identical to the reference (whose trunk is frozen at ``model.py:25-26``) but
without re-running BERT on every batch (the reference hot-loop flaw,
``model.py:41-61``).

``UserEncoder`` mirrors reference ``encoder.py:36-56``: dropout(0.2) ->
multi-head self-attention over clicked-news vectors -> additive attention ->
user vector. The reference passes no padding mask (history pad rows attend
like real clicks); ``mask`` is optional here, default None for parity.
"""

from __future__ import annotations

import jax.numpy as jnp
from flax import linen as nn

from fedrec_tpu.models.attention import AdditiveAttention, MultiHeadAttention


class TextHead(nn.Module):
    """(..., L, bert_hidden) token states -> (..., news_dim) news vector."""

    news_dim: int = 400
    bert_hidden: int = 768
    stable_softmax: bool = True
    dtype: jnp.dtype = jnp.float32
    use_pallas: bool = False

    @nn.compact
    def __call__(
        self, token_states: jnp.ndarray, mask: jnp.ndarray | None = None
    ) -> jnp.ndarray:
        # reference AdditiveAttention(hidden, hidden // 2) at encoder.py:20-21;
        # reference passes NO token mask to the pooler (encoder.py:28)
        pooled = AdditiveAttention(
            hidden=self.bert_hidden // 2,
            stable_softmax=self.stable_softmax,
            dtype=self.dtype,
            use_pallas=self.use_pallas,
            name="pool",
        )(token_states, mask)
        return nn.Dense(self.news_dim, dtype=self.dtype, name="fc")(pooled)


class UserEncoder(nn.Module):
    """(..., H, news_dim) clicked-news vectors -> (..., news_dim) user vector."""

    news_dim: int = 400
    num_heads: int = 20
    head_dim: int = 20
    query_dim: int = 200
    dropout_rate: float = 0.2
    stable_softmax: bool = True
    dtype: jnp.dtype = jnp.float32
    use_pallas: bool = False
    seq_axis: str | None = None  # shard history over this mesh axis (long context)
    seq_impl: str = "ring"
    attn_impl: str = "auto"      # see ModelConfig.attn_impl
    chunk_threshold: int = 1024

    @nn.compact
    def __call__(
        self,
        clicked_vecs: jnp.ndarray,
        mask: jnp.ndarray | None = None,
        train: bool = False,
    ) -> jnp.ndarray:
        x = nn.Dropout(self.dropout_rate, deterministic=not train)(clicked_vecs)
        x = MultiHeadAttention(
            num_heads=self.num_heads,
            head_dim=self.head_dim,
            stable_softmax=self.stable_softmax,
            dtype=self.dtype,
            use_pallas=self.use_pallas,
            seq_axis=self.seq_axis,
            seq_impl=self.seq_impl,
            attn_impl=self.attn_impl,
            chunk_threshold=self.chunk_threshold,
            name="self_attn",
        )(x, x, x, mask)
        return AdditiveAttention(
            hidden=self.query_dim,
            stable_softmax=self.stable_softmax,
            dtype=self.dtype,
            use_pallas=self.use_pallas,
            seq_axis=self.seq_axis,
            name="pool",
        )(x, mask)
