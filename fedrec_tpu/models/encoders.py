"""News text head and user encoder (Flax linen).

``TextHead`` is the trainable tail of the reference's ``TextEncoder``
(additive attention over token states + Linear 768->400, reference
``encoder.py:20-29``). The frozen DistilBERT trunk's per-news token states
are constant, so the TPU design computes them once, caches them HBM- or
host-resident, and only the head runs in the training step — numerically
identical to the reference (whose trunk is frozen at ``model.py:25-26``) but
without re-running BERT on every batch (the reference hot-loop flaw,
``model.py:41-61``).

``UserEncoder`` mirrors reference ``encoder.py:36-56``: dropout(0.2) ->
multi-head self-attention over clicked-news vectors -> additive attention ->
user vector. The reference passes no padding mask (history pad rows attend
like real clicks); ``mask`` is optional here, default None for parity.
"""

from __future__ import annotations

import jax.numpy as jnp
from flax import linen as nn

from fedrec_tpu.models.attention import AdditiveAttention, MultiHeadAttention


class TextHead(nn.Module):
    """(..., L, bert_hidden) token states -> (..., news_dim) news vector."""

    news_dim: int = 400
    bert_hidden: int = 768
    stable_softmax: bool = True
    dtype: jnp.dtype = jnp.float32
    use_pallas: bool = False

    @nn.compact
    def __call__(
        self, token_states: jnp.ndarray, mask: jnp.ndarray | None = None
    ) -> jnp.ndarray:
        # reference AdditiveAttention(hidden, hidden // 2) at encoder.py:20-21;
        # reference passes NO token mask to the pooler (encoder.py:28)
        pooled = AdditiveAttention(
            hidden=self.bert_hidden // 2,
            stable_softmax=self.stable_softmax,
            dtype=self.dtype,
            use_pallas=self.use_pallas,
            name="pool",
        )(token_states, mask)
        return nn.Dense(self.news_dim, dtype=self.dtype, name="fc")(pooled)


class CnnTextHead(nn.Module):
    """CNN text head — the NAML model family (Wu et al. 2019, "Neural News
    Recommendation with Attentive Multi-View Learning"): Conv1D over the
    frozen trunk's token states -> ReLU -> additive-attention pooling.

    A third architecture family beyond the reference's single additive
    head (reference ``encoder.py:20-29``) and the GRU/LSTUR user tower.
    TPU shape: a SAME-padded width-``kernel`` convolution lowers to one
    ``(L, kernel*hidden) x (kernel*hidden, news_dim)`` matmul per news —
    MXU-friendly, static shapes, no Python loops.

    (..., L, bert_hidden) token states -> (..., news_dim) news vector.
    """

    news_dim: int = 400
    bert_hidden: int = 768
    kernel: int = 3
    stable_softmax: bool = True
    dtype: jnp.dtype = jnp.float32
    use_pallas: bool = False

    @nn.compact
    def __call__(
        self, token_states: jnp.ndarray, mask: jnp.ndarray | None = None
    ) -> jnp.ndarray:
        x = nn.Conv(
            self.news_dim,
            kernel_size=(self.kernel,),
            padding="SAME",
            dtype=self.dtype,
            name="conv",
        )(token_states.astype(self.dtype))
        x = nn.relu(x)
        return AdditiveAttention(
            hidden=self.news_dim // 2,
            stable_softmax=self.stable_softmax,
            dtype=self.dtype,
            use_pallas=self.use_pallas,
            name="pool",
        )(x, mask)


class GRUUserEncoder(nn.Module):
    """Recurrent user tower (LSTUR family, An et al. 2019 "Neural News
    Recommendation with Long- and Short-term User Representations"):
    dropout -> GRU over the click sequence -> additive attention over the
    hidden states -> (..., news_dim) user vector.

    A second model family beyond the reference's single MHA architecture
    (reference ``encoder.py:36-56``): order-AWARE where attention+pool is
    permutation-equivariant over history. TPU-native by construction — the
    GRU is a ``lax.scan`` (via ``nn.RNN``), static shapes, no Python loop.
    Interchangeable with ``UserEncoder`` behind ``model.user_tower``; the
    parameter tree differs, so snapshots are per-family: the Trainer
    persists the resolved config as ``config.json`` next to the snapshots
    and validates ``model.user_tower`` (and the other tree-shaping knobs)
    against it on resume, failing with a guided message instead of a raw
    orbax tree error (``train/trainer.py::Trainer._check_snapshot_config``).

    Padding semantics: with ``mask=None`` (the default every call site
    uses) tail-pad rows run through the recurrence exactly like the MHA
    tower attends over them — the reference's no-mask behavior
    (``encoder.py:28``, ``dataset.py:83-85``), kept so the two towers see
    IDENTICAL inputs and accuracy rows compare towers, nothing else. Pass
    ``mask`` (1 = real click, tail-padded) to get masked semantics: the
    recurrence stops at each row's true length (``nn.RNN seq_lengths``)
    and the pool ignores pad positions.
    """

    news_dim: int = 400
    query_dim: int = 200
    dropout_rate: float = 0.2
    stable_softmax: bool = True
    dtype: jnp.dtype = jnp.float32
    use_pallas: bool = False

    @nn.compact
    def __call__(
        self,
        clicked_vecs: jnp.ndarray,
        mask: jnp.ndarray | None = None,
        train: bool = False,
    ) -> jnp.ndarray:
        x = nn.Dropout(self.dropout_rate, deterministic=not train)(clicked_vecs)
        # nn.RNN scans a GRUCell over the time axis; flatten any extra
        # leading dims to one batch dim first (eval paths pass (B, H, D),
        # per-example DP paths (1, H, D))
        lead = x.shape[:-2]
        flat = x.reshape((-1,) + x.shape[-2:])
        seq_lengths = None
        if mask is not None:
            seq_lengths = mask.reshape(-1, mask.shape[-1]).sum(-1).astype(
                jnp.int32
            )
        outs = nn.RNN(
            nn.GRUCell(self.news_dim, dtype=self.dtype), name="gru"
        )(flat, seq_lengths=seq_lengths)
        outs = outs.reshape(lead + outs.shape[-2:])
        return AdditiveAttention(
            hidden=self.query_dim,
            stable_softmax=self.stable_softmax,
            dtype=self.dtype,
            use_pallas=self.use_pallas,
            name="pool",
        )(outs, mask)


class _AttnParams(nn.Module):
    """Parameter owner for the fused path: creates ``MultiHeadAttention``'s
    exact Dense tree (names, shapes, xavier-uniform init) on a zero-length
    input without running the attention math — the module's own softmax
    cannot trace L=0, and the fused kernel does the math anyway."""

    features: int
    dtype: jnp.dtype

    @nn.compact
    def __call__(self, x0: jnp.ndarray):
        for name in ("w_q", "w_k", "w_v"):
            nn.Dense(
                self.features,
                dtype=self.dtype,
                kernel_init=nn.initializers.xavier_uniform(),
                name=name,
            )(x0)


class _PoolParams(nn.Module):
    """``AdditiveAttention``'s Dense tree for the fused path (same
    zero-length idiom as its own ``use_pallas`` branch)."""

    hidden: int
    dtype: jnp.dtype

    @nn.compact
    def __call__(self, x0: jnp.ndarray):
        fc1 = nn.Dense(self.hidden, dtype=self.dtype, name="att_fc1")
        nn.Dense(1, dtype=self.dtype, name="att_fc2")(fc1(x0))


class UserEncoder(nn.Module):
    """(..., H, news_dim) clicked-news vectors -> (..., news_dim) user vector.

    ``fuse=True`` (``model.fuse_hot_path``) routes everything after the
    dropout — Q/K/V projections, per-head attention, additive pooling, and
    (when ``cand_vecs`` is passed) candidate scoring — through ONE fused
    Pallas kernel (``fedrec_tpu.ops.fused_history_score``). The submodules
    are still constructed (zero-length calls materialize the identical
    parameter tree, so checkpoints interoperate and the dropout RNG fold is
    byte-identical to the dense path), but their math is replaced by the
    kernel. Requires ``stable_softmax`` and no sequence sharding; the
    kernel reproduces the modules' exact epsilon-normalization semantics
    (see ``fused_hot_path``'s numerics contract).
    """

    news_dim: int = 400
    num_heads: int = 20
    head_dim: int = 20
    query_dim: int = 200
    dropout_rate: float = 0.2
    stable_softmax: bool = True
    dtype: jnp.dtype = jnp.float32
    use_pallas: bool = False
    fuse: bool = False           # model.fuse_hot_path — fused kernel route
    seq_axis: str | None = None  # shard history over this mesh axis (long context)
    seq_impl: str = "ring"
    attn_impl: str = "auto"      # see ModelConfig.attn_impl
    chunk_threshold: int = 1024

    @nn.compact
    def __call__(
        self,
        clicked_vecs: jnp.ndarray,
        mask: jnp.ndarray | None = None,
        train: bool = False,
        cand_vecs: jnp.ndarray | None = None,
    ):
        fused = self.fuse and self.seq_axis is None and self.stable_softmax
        if cand_vecs is not None and not fused:
            raise ValueError(
                "UserEncoder(cand_vecs=...) is the fused-scoring entry; it "
                "requires fuse=True (model.fuse_hot_path) with "
                "stable_softmax and no sequence sharding"
            )
        x = nn.Dropout(self.dropout_rate, deterministic=not train)(clicked_vecs)
        if fused:
            from fedrec_tpu.ops import fused_history_score, fused_user_vector

            # the param-owner modules exist purely to create the IDENTICAL
            # parameter tree (names, shapes, inits — checkpoints and the
            # dense path interoperate freely, pinned in tests) on
            # zero-length inputs; the kernel does all real math
            attn = _AttnParams(
                features=self.num_heads * self.head_dim,
                dtype=self.dtype,
                name="self_attn",
            )
            pool = _PoolParams(
                hidden=self.query_dim, dtype=self.dtype, name="pool"
            )
            z = x[..., :0, :]
            attn(z)
            pool(z)
            ap = attn.variables["params"]
            pp = pool.variables["params"]
            if cand_vecs is None:
                return fused_user_vector(x, mask, ap, pp, self.num_heads)
            scores, user = fused_history_score(
                x, cand_vecs, mask, ap, pp, self.num_heads
            )
            return user, scores
        x = MultiHeadAttention(
            num_heads=self.num_heads,
            head_dim=self.head_dim,
            stable_softmax=self.stable_softmax,
            dtype=self.dtype,
            use_pallas=self.use_pallas,
            seq_axis=self.seq_axis,
            seq_impl=self.seq_impl,
            attn_impl=self.attn_impl,
            chunk_threshold=self.chunk_threshold,
            name="self_attn",
        )(x, x, x, mask)
        return AdditiveAttention(
            hidden=self.query_dim,
            stable_softmax=self.stable_softmax,
            dtype=self.dtype,
            use_pallas=self.use_pallas,
            seq_axis=self.seq_axis,
            name="pool",
        )(x, mask)
