"""Resilient TCP client for the serving protocol — retry, backoff, deadlines.

The serving wire protocol (``fedrec_tpu.serving.server``) is JSON lines
over TCP. Driving it with a bare ``asyncio.open_connection`` makes every
consumer — the load generator, an admin refresh script, a smoke test —
fall over the moment the server restarts: one ``ConnectionResetError``
and the whole run's artifact is gone. This module is the one place that
failure handling lives:

* :class:`ServingClient` — a single connection that (re)connects lazily
  with **exponential backoff + full jitter** (delay ~ U(0, base·2^n),
  capped), enforces a **per-request deadline** (default
  ``request_timeout_ms``; per-call ``deadline_ms`` wins), and converts
  transport failures into error *responses* (``{"error": "deadline"}`` /
  ``{"error": "unavailable"}``) instead of exceptions — so a server
  restart mid-run degrades to elevated latency, not a crashed driver.
  A timed-out request closes the connection (the response stream is no
  longer line-synchronized) and the next call reconnects.
* :class:`ServingClientPool` — N independent connections behind an
  ``asyncio`` queue with the same ``handle(request)`` surface as the
  in-process :class:`~fedrec_tpu.serving.server.ServingService`, so
  ``benchmarks/serve_load.py --connect host:port`` drives a live server
  with the exact closed/open-loop code that drives the in-process one.
  ``latency_ms``/``deadline_met`` are overwritten with the CLIENT-side
  round trip — the honest number once a network sits in the middle.

Also the admin client: ``admin("metrics")``, ``admin("prometheus")``,
``admin("refresh", snapshot_dir=..., token_states=...)`` — see
docs/OPERATIONS.md for the one-liner.
"""

from __future__ import annotations

import asyncio
import json
import random
import time

from fedrec_tpu.obs import wire


class ServingUnavailable(ConnectionError):
    """Raised by :meth:`ServingClient.request_or_raise` when the retry
    budget is exhausted; the plain ``request`` surface returns an error
    response instead."""


class ServingClient:
    """One JSON-lines connection with reconnect/backoff and deadlines.

    One request in flight per client (callers needing concurrency use a
    :class:`ServingClientPool`); the response to a request is the next
    line, so a lost or timed-out request invalidates the stream and the
    connection is dropped and re-established.
    """

    def __init__(
        self,
        host: str,
        port: int,
        request_timeout_ms: float = 1000.0,
        backoff_base_ms: float = 50.0,
        backoff_max_ms: float = 2000.0,
        max_attempts: int = 8,
        seed: int | None = None,
    ):
        self.host = host
        self.port = int(port)
        self.request_timeout_ms = float(request_timeout_ms)
        self.backoff_base_ms = float(backoff_base_ms)
        self.backoff_max_ms = float(backoff_max_ms)
        self.max_attempts = int(max_attempts)
        self._rng = random.Random(seed)
        self._reader: asyncio.StreamReader | None = None
        self._writer: asyncio.StreamWriter | None = None
        self._was_connected = False
        # observable retry accounting (the load generator reports these)
        self.reconnects = 0
        self.failed_requests = 0

    # ------------------------------------------------------------ plumbing
    def backoff_delay_s(self, attempt: int) -> float:
        """Full-jitter exponential backoff, delegated to the fleet-wide
        policy module (:func:`fedrec_tpu.parallel.rpc.backoff_delay_s`)
        so the serving client and the async worker's resilient RPC share
        ONE retry shape. Jitter matters as much as the exponent — a
        restarted server must not meet every client's retry in one
        synchronized stampede."""
        from fedrec_tpu.parallel.rpc import backoff_delay_s

        return backoff_delay_s(
            attempt, self.backoff_base_ms, self.backoff_max_ms, self._rng
        )

    async def _drop(self) -> None:
        w, self._reader, self._writer = self._writer, None, None
        if w is not None:
            try:
                w.close()
                await w.wait_closed()
            except (ConnectionError, OSError):
                pass

    async def _connect(self, deadline: float) -> bool:
        """(Re)connect with backoff until ``deadline`` (monotonic seconds)
        or the attempt budget runs out. True on success."""
        for attempt in range(self.max_attempts):
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return False
            try:
                self._reader, self._writer = await asyncio.wait_for(
                    asyncio.open_connection(self.host, self.port),
                    timeout=remaining,
                )
                # any re-establishment after a previous connection counts —
                # a drop followed by a clean first-attempt re-dial is still
                # a reconnect in the artifact's resilience accounting
                if self._was_connected:
                    self.reconnects += 1
                    if wire.wire_enabled():
                        wire.record_reconnect(self.host, self.port)
                self._was_connected = True
                return True
            except (OSError, asyncio.TimeoutError):
                await self._drop()
                delay = self.backoff_delay_s(attempt)
                if time.monotonic() + delay >= deadline:
                    return False
                await asyncio.sleep(delay)
        return False

    # ------------------------------------------------------------ requests
    async def request(self, payload: dict, deadline_ms: float | None = None) -> dict:
        """One request/response with retry inside the deadline.

        Returns the server's response dict, or ``{"error": "deadline"}`` /
        ``{"error": "unavailable"}`` when the deadline passed or every
        reconnect attempt failed — never raises for transport failures.
        """
        budget_ms = deadline_ms if deadline_ms is not None else self.request_timeout_ms
        deadline = time.monotonic() + budget_ms / 1e3
        # wire envelope (obs.wire): additive trace context + per-edge
        # RTT/offset telemetry; rebuilt per attempt so a retried request
        # carries fresh send_ts.  Off -> byte-identical pre-envelope line.
        op = str(payload.get("cmd", "score"))
        attempt = 0
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                self.failed_requests += 1
                self._wire_error(op)
                return {"error": "deadline"}
            if self._writer is None and not await self._connect(deadline):
                self.failed_requests += 1
                self._wire_error(op)
                return {"error": "unavailable"}
            req_env = (
                wire.request_envelope(op) if wire.wire_enabled() else None
            )
            line = (json.dumps(
                {**payload, wire.WIRE_KEY: req_env}
                if req_env is not None else payload
            ) + "\n").encode()
            t0 = time.perf_counter()
            try:
                self._writer.write(line)
                await asyncio.wait_for(
                    self._writer.drain(), deadline - time.monotonic()
                )
                raw = await asyncio.wait_for(
                    self._reader.readline(), max(deadline - time.monotonic(), 0)
                )
            except asyncio.TimeoutError:
                # the stream is no longer line-synchronized; drop it
                await self._drop()
                self.failed_requests += 1
                self._wire_error(op)
                return {"error": "deadline"}
            except (ConnectionError, OSError):
                # server went away mid-request (restart): reconnect and
                # retry while the deadline allows
                await self._drop()
                delay = self.backoff_delay_s(attempt)
                attempt += 1
                if time.monotonic() + delay >= deadline:
                    self.failed_requests += 1
                    self._wire_error(op)
                    return {"error": "unavailable"}
                await asyncio.sleep(delay)
                continue
            if not raw:  # clean EOF: server closed on us — retry like a reset
                await self._drop()
                delay = self.backoff_delay_s(attempt)
                attempt += 1
                if time.monotonic() + delay >= deadline:
                    self.failed_requests += 1
                    self._wire_error(op)
                    return {"error": "unavailable"}
                await asyncio.sleep(delay)
                continue
            try:
                resp = json.loads(raw)
            except json.JSONDecodeError:
                await self._drop()
                self.failed_requests += 1
                self._wire_error(op)
                return {"error": "bad_response"}
            ack_ts = time.time()
            resp, resp_env = wire.unwrap_envelope(resp)
            if req_env is not None:
                wire.record_client_exchange(
                    self.host, self.port, op, req_env, resp_env,
                    bytes_sent=len(line), bytes_recvd=len(raw),
                    rtt_s=time.perf_counter() - t0, ack_ts=ack_ts,
                )
            return resp

    def _wire_error(self, op: str) -> None:
        if wire.wire_enabled():
            wire.record_client_error(self.host, self.port, op)

    async def request_or_raise(
        self, payload: dict, deadline_ms: float | None = None
    ) -> dict:
        resp = await self.request(payload, deadline_ms=deadline_ms)
        if resp.get("error") in ("deadline", "unavailable"):
            raise ServingUnavailable(
                f"{self.host}:{self.port} — {resp['error']}"
            )
        return resp

    async def admin(self, cmd: str, deadline_ms: float | None = None, **kw) -> dict:
        """Admin command (``metrics`` / ``prometheus`` / ``refresh``) —
        refreshes load a checkpoint and recompile, so give them a real
        deadline (e.g. ``deadline_ms=120_000``)."""
        return await self.request({"cmd": cmd, **kw}, deadline_ms=deadline_ms)

    async def close(self) -> None:
        await self._drop()


class ServingClientPool:
    """N :class:`ServingClient` connections behind a checkout queue,
    presenting the in-process service's ``handle(request)`` surface."""

    def __init__(self, host: str, port: int, size: int = 8, **client_kw):
        self.clients = [
            ServingClient(host, port, seed=i, **client_kw) for i in range(size)
        ]
        self._q: asyncio.Queue = asyncio.Queue()
        for c in self.clients:
            self._q.put_nowait(c)

    async def handle(self, req: dict) -> dict:
        cli = await self._q.get()
        try:
            t0 = time.perf_counter()
            deadline_ms = req.get("deadline_ms")
            resp = await cli.request(req, deadline_ms=deadline_ms)
            rtt_ms = (time.perf_counter() - t0) * 1e3
            if "error" not in resp:
                # client-observed latency replaces the server's own number:
                # with a network (and reconnects) in the path, the RTT is
                # the truth the load artifact must carry
                resp["latency_ms"] = rtt_ms
                resp["deadline_met"] = (
                    rtt_ms <= deadline_ms if deadline_ms else True
                )
            return resp
        finally:
            self._q.put_nowait(cli)

    async def admin(self, cmd: str, deadline_ms: float | None = None, **kw) -> dict:
        cli = await self._q.get()
        try:
            return await cli.admin(cmd, deadline_ms=deadline_ms, **kw)
        finally:
            self._q.put_nowait(cli)

    def retry_metrics(self) -> dict:
        return {
            "connections": len(self.clients),
            "reconnects": sum(c.reconnects for c in self.clients),
            "failed_requests": sum(c.failed_requests for c in self.clients),
        }

    async def close(self) -> None:
        for c in self.clients:
            await c.close()
