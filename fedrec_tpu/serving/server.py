"""Long-lived TCP/JSON-lines recommendation service.

Wire protocol (one JSON object per line, newline-terminated, responses
carry the request's ``id`` back so clients may pipeline):

* request  ``{"id": 7, "history": [12, 94, ...], "top_k": 10,
  "deadline_ms": 50}`` →
  response ``{"id": 7, "ids": [...], "scores": [...], "generation": 3,
  "deadline_met": true, "latency_ms": 4.1}``
  (plus ``"news": [nid, ...]`` when the service holds an id map);
* admin    ``{"cmd": "metrics"}`` → ``{"metrics": {...}}``;
* admin    ``{"cmd": "prometheus"}`` → ``{"prometheus": "<text exposition>"}``
  (the whole obs registry in Prometheus text format, docs/OBSERVABILITY.md);
* admin    ``{"cmd": "refresh", "snapshot_dir": "...",
  "token_states": "...npy"}`` → hot-swap the embedding store from a
  training checkpoint and report the new generation;
* errors   ``{"id": ..., "error": "backpressure" | "bad_json" | ...}``.

The service composes the three serving pieces: every batch flush grabs
ONE :class:`~fedrec_tpu.serving.store.Generation` snapshot and scores the
whole batch against it (swap-atomicity: no request ever sees a torn
generation), through a per-generation retrieval function (two-stage past
the exact threshold, dense below it).  Per-generation compiled functions
are cached two generations deep, so responses for the outgoing
generation keep flowing while the incoming one warms up.

Metrics are JSON-lines through :class:`fedrec_tpu.utils.logging.MetricLogger`
(the training side's schema): ``serve.p50_ms`` / ``serve.p99_ms``,
``serve.mean_occupancy``, ``serve.swap_count``, ``serve.generation``,
``serve.staleness_sec``, plus batcher counters.
"""

from __future__ import annotations

import asyncio
import json
import time
from collections import deque
from functools import partial
from pathlib import Path
from typing import Any

import numpy as np

from fedrec_tpu.obs import get_registry
from fedrec_tpu.obs import wire
from fedrec_tpu.serving.batcher import Backpressure, MicroBatcher
from fedrec_tpu.serving.retrieval import build_index, build_two_stage_fn
from fedrec_tpu.serving.store import EmbeddingStore, EmptyStoreError

_FN_CACHE_GENERATIONS = 2


class ServingService:
    """batcher -> store -> retrieval, one object an event loop can own."""

    def __init__(
        self,
        model,
        store: EmbeddingStore,
        history_len: int,
        top_k: int = 10,
        exclude_history: bool = True,
        batch_sizes=(1, 8, 32, 128),
        flush_ms: float = 2.0,
        max_queue: int = 1024,
        num_clusters: int = 0,
        n_probe: int = 8,
        exact_threshold: int = 4096,
        id_map: dict[int, str] | None = None,
        latency_window: int = 8192,
        registry=None,
        watch=None,
    ):
        self.model = model
        self.store = store
        # in-process watch layer (fedrec_tpu.obs.watch.Watch, built by the
        # CLI when obs.slo.enabled): evaluated at heartbeat cadence in
        # serve_forever, fed drift-probe results on refresh, surfaced via
        # the admin {"cmd": "alerts"}. None = exact pre-watch behavior.
        self.watch = watch
        self.top_k = int(top_k)
        self.exclude_history = exclude_history
        self.num_clusters = int(num_clusters)
        self.n_probe = int(n_probe)
        self.exact_threshold = int(exact_threshold)
        self.id_map = id_map
        self.registry = registry or get_registry()
        self.batcher = MicroBatcher(
            self._score_batch,
            history_len=history_len,
            batch_sizes=batch_sizes,
            flush_ms=flush_ms,
            max_queue=max_queue,
            registry=self.registry,
        )
        self._fns: dict[int, Any] = {}
        self._latencies: deque[float] = deque(maxlen=latency_window)
        self._conns: set = set()  # live TCP writers; closed on stop()
        self._started_at = time.time()
        # derived gauges refreshed lazily at snapshot/exposition time (a
        # registry collector): percentile math per scrape, not per request
        self._g_p50 = self.registry.gauge("serve.p50_ms", "median serve latency")
        self._g_p99 = self.registry.gauge("serve.p99_ms", "p99 serve latency")
        self._g_occ = self.registry.gauge(
            "serve.mean_occupancy", "mean real-requests/bucket over served batches"
        )
        self._g_staleness = self.registry.gauge(
            "serve.staleness_sec", "seconds since the serving generation was published"
        )
        self._g_uptime = self.registry.gauge("serve.uptime_sec", "service uptime")
        self.registry.register_collector(self._collect)

    def _collect(self) -> None:
        lat = np.asarray(self._latencies, np.float64)
        if lat.size:
            self._g_p50.set(float(np.percentile(lat, 50)))
            self._g_p99.set(float(np.percentile(lat, 99)))
        occ = self.batcher.metrics().get("mean_occupancy")
        if occ is not None:
            self._g_occ.set(occ)
        staleness = self.store.metrics().get("staleness_sec")
        if staleness is not None:
            self._g_staleness.set(staleness)
        self._g_uptime.set(time.time() - self._started_at)

    # ------------------------------------------------------------ lifecycle
    async def start(self) -> None:
        await self.batcher.start()

    async def stop(self) -> None:
        await self.batcher.stop()
        # close surviving connections: a stopped service answering
        # "batcher not started" errors forever would pin well-behaved
        # retrying clients (fedrec_tpu.serving.client) to a dead endpoint —
        # an explicit close makes them back off and reconnect to whatever
        # replaces us
        for w in list(self._conns):
            try:
                w.close()
            except Exception:  # noqa: BLE001 — teardown best-effort
                pass
        self._conns.clear()
        # one final refresh so post-stop exposition/artifact dumps carry the
        # service's last numbers, then detach: a stopped service must not
        # keep publishing through the process registry (tests build many
        # short-lived services)
        self._collect()
        self.registry.unregister_collector(self._collect)

    def warmup(self) -> None:
        """Compile every batch bucket against the current generation so the
        first real requests don't pay XLA compile latency."""
        gen = self.store.current()
        self._cache_fn(
            gen.generation,
            self._build_fn(gen.news_vecs, gen.valid_mask, gen.user_params),
        )

    # ------------------------------------------------------------ scoring
    def _build_fn(self, news_vecs, valid_mask, user_params=None):
        """Index + compiled scorer for one generation's arrays; with
        ``user_params`` given, also run every batch bucket once so the jit
        cache is hot before the function serves traffic."""
        index = build_index(
            news_vecs,
            num_clusters=self.num_clusters,
            n_probe=self.n_probe,
            valid_mask=valid_mask,
            exact_threshold=self.exact_threshold,
        )
        fn = build_two_stage_fn(
            self.model,
            index,
            top_k=self.top_k,
            exclude_history=self.exclude_history,
        )
        if user_params is not None:
            for b in self.batcher.batch_sizes:
                hist = np.zeros((b, self.batcher.history_len), np.int32)
                np.asarray(fn(user_params, hist)[0])
        return fn

    def _cache_fn(self, generation: int, fn) -> None:
        self._fns[generation] = fn
        for g in sorted(self._fns)[:-_FN_CACHE_GENERATIONS]:
            del self._fns[g]

    def _fn_for(self, gen):
        """Lazy path: generations published directly on the store (tests,
        in-process swaps) build their scorer on first use.  The refresh
        command never takes this path — it pre-builds off the loop."""
        fn = self._fns.get(gen.generation)
        if fn is None:
            fn = self._build_fn(gen.news_vecs, gen.valid_mask)
            self._cache_fn(gen.generation, fn)
        return fn

    def _score_batch(self, hist: np.ndarray):
        """Batcher callback: one generation snapshot per batch — the
        atomic-swap contract lives in this single ``current()`` read."""
        gen = self.store.current()
        fn = self._fn_for(gen)
        ids, scores = fn(gen.user_params, hist)
        return np.asarray(ids), np.asarray(scores), gen.generation

    # ------------------------------------------------------------ requests
    async def handle(self, req: dict) -> dict:
        if not isinstance(req, dict):
            return {"error": "bad_request"}
        if "cmd" in req:
            return await self._admin(req)
        rid = req.get("id")
        try:
            result = await self.batcher.submit(
                req.get("history") or [], deadline_ms=req.get("deadline_ms")
            )
        except Backpressure:
            return {"id": rid, "error": "backpressure"}
        except EmptyStoreError:
            return {"id": rid, "error": "no_generation"}
        except Exception as e:  # noqa: BLE001 — per-request error isolation
            return {"id": rid, "error": f"{type(e).__name__}: {e}"}
        self._latencies.append(result.latency_ms)
        keep = result.ids >= 0
        want = req.get("top_k")
        if isinstance(want, bool):  # JSON true/false is not a count
            want = None
        if isinstance(want, int) and want >= 0:
            keep &= np.arange(result.ids.shape[0]) < want
        ids = [int(i) for i in result.ids[keep]]
        resp = {
            "id": rid,
            "ids": ids,
            "scores": [round(float(s), 5) for s in result.scores[keep]],
            "generation": result.generation,
            "deadline_met": result.deadline_met,
            "latency_ms": round(result.latency_ms, 3),
        }
        if want is not None and want > self.top_k:
            # the scorer is compiled at the service's --top-k; say the cap
            # applied rather than letting a short list read as "catalog
            # exhausted"
            resp["top_k_capped"] = self.top_k
        if self.id_map is not None:
            resp["news"] = [self.id_map.get(i, str(i)) for i in ids]
        return resp

    async def _admin(self, req: dict) -> dict:
        cmd = req.get("cmd")
        if cmd == "metrics":
            return {"metrics": self.metrics()}
        if cmd == "alerts":
            # active + recent alerts from the in-process watch; an
            # un-watched server answers the empty shape, not an error —
            # the command is part of the admin contract either way
            # (strict-superset pin in tests/test_watch.py)
            if self.watch is not None:
                return {"alerts": self.watch.engine.snapshot_state()}
            return {"alerts": {"active": [], "recent": []}}
        if cmd == "prometheus":
            # text exposition over the admin protocol: a scraper sidecar
            # (or curl | promtool) gets the full registry, not just the
            # serving keys — the one-line Prometheus integration
            return {"prometheus": self.registry.to_prometheus()}
        if cmd == "refresh":
            try:
                prepared = await asyncio.get_running_loop().run_in_executor(
                    None, partial(self._prepare_refresh, req)
                )
            except Exception as e:  # noqa: BLE001 — refresh must not kill serving
                return {"error": f"refresh_failed: {type(e).__name__}: {e}"}
            # publish + scorer-cache insert together ON the event loop: the
            # expensive work (checkpoint load, corpus encode, index build,
            # per-bucket compiles) already happened in the executor, so the
            # swap itself is two reference assignments no batch flush can
            # interleave with — a swap costs a warmup, never an outage
            table, user_params, valid_mask, round_, source, fn = prepared
            gen = self.store.publish(
                table, user_params, valid_mask=valid_mask,
                round=round_, source=source,
            )
            self._cache_fn(gen.generation, fn)
            if self.watch is not None:
                # unified trigger path: a drift-probe breach on this swap
                # pulses the serve:drift alert (scored at the next beat)
                self.watch.ingest_drift(self.store.metrics())
            return {"refreshed": True, "generation": gen.generation,
                    "round": gen.round, "source": gen.source}
        return {"error": f"unknown_cmd: {cmd}"}

    def _prepare_refresh(self, req: dict):
        """Checkpoint -> encode -> index build -> bucket warmup, all off the
        event loop.  Returns everything `_admin` needs for the (cheap,
        on-loop) publish; in-flight batches keep serving the old generation
        from its cached scorer throughout."""
        import jax.numpy as jnp

        from fedrec_tpu.serving.store import load_checkpoint_params
        from fedrec_tpu.train.step import encode_all_news

        token_states = np.load(req["token_states"])
        user_params, news_params, round_, kind = load_checkpoint_params(
            req["snapshot_dir"]
        )
        table = encode_all_news(
            self.model, news_params,
            jnp.asarray(token_states, jnp.dtype(req.get("dtype", "float32"))),
        )
        if "valid_mask" in req:
            valid_mask = np.load(req["valid_mask"]).astype(bool)
            if valid_mask.shape[0] != table.shape[0]:
                raise ValueError(
                    f"valid_mask length {valid_mask.shape[0]} != catalog "
                    f"{table.shape[0]}"
                )
        else:
            # reuse the serving mask only while the catalog size is
            # unchanged — a grown/shrunk corpus would shape-error (or,
            # same-size reordered, silently validate the WRONG rows), so a
            # refresh that changes N must ship its own mask or serve all
            valid_mask = self.store.current().valid_mask
            if valid_mask is not None and valid_mask.shape[0] != table.shape[0]:
                valid_mask = None
        fn = self._build_fn(table, valid_mask, user_params)
        return table, user_params, valid_mask, round_, f"checkpoint:{kind}", fn

    # ------------------------------------------------------------ metrics
    def metrics(self) -> dict:
        lat = np.asarray(self._latencies, np.float64)
        out = {
            "uptime_sec": round(time.time() - self._started_at, 1),
            "latency_count": int(lat.size),
            "p50_ms": round(float(np.percentile(lat, 50)), 3) if lat.size else None,
            "p99_ms": round(float(np.percentile(lat, 99)), 3) if lat.size else None,
        }
        out.update(self.batcher.metrics())
        out.update(self.store.metrics())
        return out

    def log_metrics(self, logger, step: int) -> None:
        """Emit the metric snapshot through the training side's
        MetricLogger schema (``serve.``-prefixed keys)."""
        logger.log(step, {f"serve.{k}": v for k, v in self.metrics().items()
                          if not isinstance(v, dict)})


# ---------------------------------------------------------------- TCP layer
async def _handle_conn(service: ServingService, reader, writer) -> None:
    write_lock = asyncio.Lock()
    tasks: set[asyncio.Task] = set()
    service._conns.add(writer)

    async def one(raw: bytes) -> None:
        # wire envelope (obs.wire): stripped BEFORE dispatch so unknown
        # envelope keys never reach handle(); the reply echoes one ONLY
        # when the request carried one (old clients see pre-envelope
        # bytes).  contextvars make the serve ctx task-local here.
        recv_ts = time.time()
        env = reply_env = None
        try:
            req = json.loads(raw)
        except json.JSONDecodeError:
            resp: dict = {"error": "bad_json"}
        else:
            req, env = wire.unwrap_envelope(req)
            if env is None:
                resp = await service.handle(req)
            else:
                token = wire.enter_serve(env, recv_ts)
                try:
                    resp = await service.handle(req)
                    reply_env = wire.server_reply_envelope(env, recv_ts)
                finally:
                    wire.exit_serve(token)
                if isinstance(resp, dict):
                    resp = {**resp, wire.WIRE_KEY: reply_env}
        out = (json.dumps(resp) + "\n").encode()
        async with write_lock:
            writer.write(out)
            try:
                await writer.drain()
            except ConnectionError:
                pass
        if env is not None and reply_env is not None:
            wire.record_server_exchange(
                env, reply_env, op=str(env.get("op") or "score"),
                bytes_recvd=len(raw), bytes_sent=len(out),
            )

    while True:
        try:
            line = await reader.readline()
        except (asyncio.LimitOverrunError, ValueError):
            # request line beyond the stream limit (see _LINE_LIMIT): answer
            # with an explicit error instead of tearing the connection down
            # silently; the stream is no longer line-synchronized, so close
            async with write_lock:
                writer.write(b'{"error": "line_too_long"}\n')
                try:
                    await writer.drain()
                except ConnectionError:
                    pass
            break
        if not line:
            break
        if line.strip():
            # task-per-request: requests on one connection pipeline through
            # the batcher instead of serializing on each other's latency
            t = asyncio.ensure_future(one(line))
            tasks.add(t)
            t.add_done_callback(tasks.discard)
    if tasks:
        await asyncio.gather(*tasks, return_exceptions=True)
    service._conns.discard(writer)
    try:
        writer.close()
        await writer.wait_closed()
    except ConnectionError:
        pass


# request lines carry full click histories; asyncio's 64 KiB default would
# cut off a few-thousand-click history mid-line
_LINE_LIMIT = 1 << 20


async def start_server(
    service: ServingService, host: str = "127.0.0.1", port: int = 0
):
    """Start the batcher and the TCP listener; returns the asyncio server
    (``server.sockets[0].getsockname()`` has the bound port when 0)."""
    await service.start()
    return await asyncio.start_server(
        partial(_handle_conn, service), host, port, limit=_LINE_LIMIT
    )


async def serve_forever(
    service: ServingService,
    host: str = "127.0.0.1",
    port: int = 7607,
    metrics_every_s: float = 30.0,
    logger=None,
    obs_dir: str | None = None,
    jsonl_max_mb: float = 0.0,
) -> None:
    """CLI entry loop: listen until SIGINT/SIGTERM, logging metrics
    periodically.  Shutdown is graceful BY CONSTRUCTION: the signal only
    sets an event, so the in-flight batch completes, the listener closes,
    and the batcher drain fails queued requests cleanly — instead of the
    default handler tearing the loop down mid-batch."""
    import signal

    if obs_dir is not None:
        Path(obs_dir).mkdir(parents=True, exist_ok=True)
    server = await start_server(service, host, port)
    addr = server.sockets[0].getsockname()
    print(f"[serve] listening on {addr[0]}:{addr[1]}", flush=True)
    stop = asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGINT, signal.SIGTERM):
        try:
            loop.add_signal_handler(sig, stop.set)
        except (NotImplementedError, RuntimeError):  # non-main thread / win
            pass
    step = 0

    async def beat() -> None:
        nonlocal step
        while True:
            await asyncio.sleep(metrics_every_s)
            step += 1
            if logger is not None:
                service.log_metrics(logger, step)
            if service.watch is not None:
                # heartbeat-cadence watch tick, fed the serve.* metric
                # snapshot so SLOs over serve.p99_ms etc. read fresh
                # values without waiting on a registry collector pass
                service.watch.evaluate(record={
                    f"serve.{k}": v for k, v in service.metrics().items()
                    if isinstance(v, (int, float))
                    and not isinstance(v, bool)
                })
            if obs_dir is not None:
                # periodic registry snapshots make the event log useful
                # even when the server is killed rather than signalled;
                # size-rotate first so a long-lived server cannot fill
                # the disk (obs.jsonl_max_mb)
                from fedrec_tpu.obs import rotate_jsonl

                rotate_jsonl(Path(obs_dir) / "metrics.jsonl", jsonl_max_mb)
                service.registry.write_snapshot(Path(obs_dir) / "metrics.jsonl")

    heartbeat = asyncio.ensure_future(beat())
    try:
        await stop.wait()
        print("[serve] signal received; draining", flush=True)
    finally:
        heartbeat.cancel()
        server.close()
        await server.wait_closed()
        await service.stop()
        if obs_dir is not None:
            from fedrec_tpu.obs import dump_artifacts

            paths = dump_artifacts(obs_dir, registry=service.registry)
            print(f"[serve] obs artifacts in {obs_dir}: "
                  f"{', '.join(sorted(paths))}", flush=True)
