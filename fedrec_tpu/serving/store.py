"""Versioned news-embedding store with atomic hot-swap.

A federated trainer produces a new global model every round; a long-lived
server must pick those up WITHOUT restarting and WITHOUT any request ever
observing a half-updated state (user params from round R scoring news
vectors from round R+1 would silently corrupt every score).

The store holds immutable :class:`Generation` snapshots.  ``publish``
builds the complete new generation first and then swaps it in with ONE
reference assignment — atomic under the GIL, and doubly so under the
single-threaded asyncio server.  Readers call ``current()`` exactly once
per batch and score the whole batch against that snapshot, so a swap
mid-stream only affects which generation LATER batches see, never the
internal consistency of an in-flight one.

Staleness is first-class: every generation records the federated round it
came from (when known) and its publish time, and ``metrics()`` exposes
``generation`` / ``swap_count`` / ``staleness_sec`` so an operator can
alarm on a server that stopped tracking the trainer.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from pathlib import Path
from typing import Any

import numpy as np

from fedrec_tpu.obs import get_registry


class EmptyStoreError(RuntimeError):
    """``current()`` before any generation was published."""


@dataclass(frozen=True)
class Generation:
    """One immutable serving snapshot.  All fields are set at build time;
    requests served from a generation see exactly these arrays."""

    generation: int
    news_vecs: Any                    # (N, D) news-vector table
    user_params: Any                  # user-tower param tree
    valid_mask: np.ndarray | None     # (N,) bool; False rows never served
    round: int | None                 # federated round, when known
    source: str                       # "synthetic" | "checkpoint" | ...
    published_at: float

    @property
    def num_news(self) -> int:
        return int(self.news_vecs.shape[0])


class EmbeddingStore:
    """Holds the current :class:`Generation` and swap bookkeeping.

    Thread-safe by construction for readers (one attribute read); writers
    serialize on a lock only to keep ``generation`` numbers and
    ``swap_count`` consistent if two publishers ever race.
    """

    def __init__(self, clock=time.time, registry=None, drift_probe=None):
        self._clock = clock
        self._lock = threading.Lock()
        self._gen: Generation | None = None
        self._swap_count = 0
        self._registry = registry or get_registry()
        reg = self._registry
        self._g_generation = reg.gauge(
            "serve.generation", "embedding-store generation being served"
        )
        self._g_swaps = reg.gauge(
            "serve.swap_count", "hot-swaps since the store was created"
        )
        self._g_num_news = reg.gauge(
            "serve.num_news", "catalog rows in the current generation"
        )
        # pre-swap quality probe (obs.quality): scores a pinned probe set
        # against the outgoing AND incoming generation BEFORE the swap, so
        # a bad table push shows non-zero serve.drift_* metrics before it
        # serves traffic. None = the exact pre-quality publish path.
        self._drift = drift_probe

    def enable_drift_probe(
        self, num_probes: int = 32, topk: int = 10, seed: int = 0
    ) -> None:
        """Arm the pre-swap drift probe (``obs.quality.probe_users`` /
        ``probe_topk`` in the serving CLI; tests arm it directly)."""
        from fedrec_tpu.obs.quality import DriftProbe

        self._drift = DriftProbe(
            num_probes=num_probes, topk=topk, seed=seed,
            registry=self._registry,
        )

    # ------------------------------------------------------------ readers
    def current(self) -> Generation:
        gen = self._gen
        if gen is None:
            raise EmptyStoreError("no generation published yet")
        return gen

    @property
    def generation(self) -> int:
        return self.current().generation

    @property
    def swap_count(self) -> int:
        return self._swap_count

    def metrics(self) -> dict:
        gen = self._gen
        if gen is None:
            return {"generation": None, "swap_count": self._swap_count}
        out = {
            "generation": gen.generation,
            "swap_count": self._swap_count,
            "round": gen.round,
            "source": gen.source,
            "num_news": gen.num_news,
            "staleness_sec": round(self._clock() - gen.published_at, 3),
        }
        if self._drift is not None and self._drift.last is not None:
            # the last pre-swap probe verdict rides the admin metrics dict
            # (strict superset of the pre-quality keys)
            out.update({
                f"drift_{k}": v for k, v in self._drift.last.items()
                if isinstance(v, (int, float, bool))
            })
        return out

    # ------------------------------------------------------------ writers
    def publish(
        self,
        news_vecs,
        user_params,
        valid_mask: np.ndarray | None = None,
        round: int | None = None,
        source: str = "manual",
    ) -> Generation:
        """Build the full new generation, then swap it in atomically.
        The first publish is generation 0 and does not count as a swap.
        With a drift probe armed, the incoming table is scored against
        the outgoing one BEFORE the swap (serve.drift_* metrics) — a
        probe failure is reported, never allowed to block the publish."""
        with self._lock:
            prev = self._gen
            if self._drift is not None and prev is not None:
                try:
                    self._drift.compare(
                        np.asarray(prev.news_vecs), prev.valid_mask,
                        np.asarray(news_vecs), valid_mask,
                    )
                except Exception as e:  # noqa: BLE001 — the probe is telemetry;
                    # a malformed table must still reach the swap's own
                    # validation rather than dying in the probe
                    print(f"[store] drift probe failed: {type(e).__name__}: {e}")
            gen = Generation(
                generation=0 if prev is None else prev.generation + 1,
                news_vecs=news_vecs,
                user_params=user_params,
                valid_mask=valid_mask,
                round=round,
                source=source,
                published_at=self._clock(),
            )
            self._gen = gen  # the one atomic publish point
            if prev is not None:
                self._swap_count += 1
            self._g_generation.set(gen.generation)
            self._g_swaps.set(self._swap_count)
            self._g_num_news.set(gen.num_news)
            return gen


def shard_news_vecs(
    news_vecs, devices: list | None = None
) -> tuple[Any, int]:
    """Row-shard an ``(N, D)`` news-vector table across this process's
    devices — the serving half of the sharded catalog (``shard.table``):
    per-device HBM holds ``ceil(N / n_devices)`` rows instead of N, so a
    million-item catalog serves from a slice without the k-means index
    being the only option.

    Returns ``(sharded_table, real_rows)``: the table zero-padded to a
    device-count multiple and committed to a 1-D ``rows`` mesh
    (``NamedSharding``), plus the real row count. Pad rows must never be
    served — :func:`publish_sharded` masks them via ``valid_mask``, which
    both the exact scorer and the index build respect. The jitted exact
    scorer consumes the sharded table transparently (XLA inserts the
    collectives where a consumer needs replication).
    """
    import jax
    from jax.sharding import Mesh

    from fedrec_tpu.shard.table import ShardedNewsTable

    devices = list(devices) if devices is not None else jax.local_devices()
    mesh = Mesh(np.asarray(devices), ("rows",))
    # ONE pad-and-commit rule for train- and serve-side tables: delegate
    # to the sharding subsystem's constructor so the two can never diverge
    tab = ShardedNewsTable.create(news_vecs, mesh, "rows")
    return tab.rows, tab.spec.num_rows


def publish_sharded(
    store: EmbeddingStore,
    news_vecs,
    user_params,
    valid_mask: np.ndarray | None = None,
    round: int | None = None,
    source: str = "manual",
    devices: list | None = None,
    registry=None,
) -> Generation:
    """:meth:`EmbeddingStore.publish` with the table row-sharded across
    local devices (:func:`shard_news_vecs`). Pad rows get ``valid_mask``
    False so retrieval can never emit them; the
    ``shard.table_rows_per_device`` gauge records the per-device
    residency. Atomicity is inherited — the sharded table is built fully
    before the one publish point swaps it in."""
    sharded, n = shard_news_vecs(news_vecs, devices=devices)
    padded = int(sharded.shape[0])
    mask = np.zeros(padded, bool)
    mask[:n] = True if valid_mask is None else np.asarray(valid_mask, bool)[:n]
    reg = registry or get_registry()
    n_dev = max(
        len(devices) if devices is not None else len(sharded.devices()), 1
    )
    reg.gauge(
        "shard.table_rows_per_device",
        "news-catalog rows resident per device (= catalog rows under "
        "the replicated layout; padded_rows / shards under shard.table)",
    ).set(padded / n_dev)
    return store.publish(
        sharded,
        user_params,
        valid_mask=mask,
        round=round,
        source=f"{source}:sharded",
    )


def load_checkpoint_params(
    snap_dir: str | Path, log=None
) -> tuple[Any, Any, int | None, str]:
    """Restore ``(user_params, news_params, round, kind)`` from whichever
    snapshot format in ``snap_dir`` was written most recently.

    THE restore policy, shared by the one-shot CLI
    (:mod:`fedrec_tpu.cli.recommend`) and the online server: orbax trees
    (fedrec-run) and coordinator flax-msgpack globals can coexist in one
    directory, and round counters are per-run (a 50-round fedrec-run must
    not shadow a later 20-round coordinator deployment), so the tie-break
    is the artifacts' own mtimes.  Params come back as HOST arrays so the
    serving jit places them itself (an orbax restore can carry the
    training run's device placement).  ``log`` (optional callable) gets
    operator-facing diagnostics like the both-formats-present notice.
    """
    import jax

    from fedrec_tpu.train.checkpoint import SnapshotManager, coordinator_globals

    snap_dir = Path(snap_dir)
    snapshots = SnapshotManager(snap_dir)
    orbax_round = snapshots.latest_round()
    globals_ = coordinator_globals(snap_dir)

    def _mtime(path: Path) -> float:
        try:
            return path.stat().st_mtime
        except OSError:
            return 0.0

    orbax_mtime = (
        _mtime(snap_dir / str(orbax_round)) if orbax_round is not None else 0.0
    )
    global_mtime = _mtime(globals_[-1]) if globals_ else 0.0
    if log is not None and orbax_round is not None and globals_:
        newer = "orbax" if orbax_mtime >= global_mtime else "coordinator"
        log(f"both orbax (round {orbax_round}) and coordinator globals in "
            f"{snap_dir}; serving the most recently written ({newer})")

    if orbax_round is not None and (not globals_ or orbax_mtime >= global_mtime):
        raw = snapshots.restore_raw()
        snapshots.close()
        # client 0 is the post-aggregation convention (all clients identical
        # after param_avg/coordinator sync — Trainer._client0_params)
        client0 = jax.tree_util.tree_map(lambda x: np.asarray(x[0]), raw)
        return client0["user_params"], client0["news_params"], orbax_round, "orbax"
    if globals_:
        snapshots.close()
        from flax import serialization

        raw = None
        for cand in reversed(globals_):
            try:
                raw = serialization.msgpack_restore(cand.read_bytes())
                break
            except FileNotFoundError:
                continue  # concurrent retention pass; writes are atomic
        if raw is None:
            raise FileNotFoundError(f"coordinator globals vanished under {snap_dir}")
        user = jax.tree_util.tree_map(np.asarray, raw["user"])
        news = jax.tree_util.tree_map(np.asarray, raw["news"])
        return user, news, int(raw["round"]), "coordinator"
    snapshots.close()
    raise FileNotFoundError(
        f"no orbax snapshot or coordinator global under {snap_dir}"
    )


def publish_from_checkpoint(
    store: EmbeddingStore,
    model,
    snap_dir: str | Path,
    token_states: np.ndarray,
    valid_mask: np.ndarray | None = None,
    dtype: str = "float32",
    shard: bool = False,
) -> Generation:
    """Refresh flow: checkpoint -> ``encode_all_news`` -> atomic publish.

    ``token_states`` is the (N, L, bert_hidden) cached-trunk table the
    table/head modes serve from (the finetune path would re-encode tokens;
    the server keeps that out of the hot path by requiring states here).
    ``shard`` routes through :func:`publish_sharded` — the table lands
    row-sharded across local devices instead of replicated.
    """
    import jax.numpy as jnp

    from fedrec_tpu.train.step import encode_all_news

    user_params, news_params, round_, kind = load_checkpoint_params(snap_dir)
    table = encode_all_news(
        model, news_params, jnp.asarray(token_states, jnp.dtype(dtype))
    )
    if shard:
        return publish_sharded(
            store, table, user_params, valid_mask=valid_mask,
            round=round_, source=f"checkpoint:{kind}",
        )
    return store.publish(
        table,
        user_params,
        valid_mask=valid_mask,
        round=round_,
        source=f"checkpoint:{kind}",
    )
