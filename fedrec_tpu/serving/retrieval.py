"""Two-stage retrieval: JAX k-means coarse quantizer + exact rerank.

Full-catalog dense scoring is one ``(B, D) x (D, N)`` matmul — fine at
MIND scale (N≈65k), but the ROADMAP's million-item catalog turns every
request into a 400 MFLOP scan of mostly-irrelevant items.  The standard
IVF answer: cluster the news vectors once per generation (Lloyd's
k-means, jitted), and at query time score the user against the C
centroids, probe the ``n_probe`` best clusters, and exactly rerank only
their members — ``n_probe/C`` of the catalog touched per request, with
recall measured (not assumed) against brute force by
:func:`recall_at_k`.

Everything keeps the serving shape discipline: the member table is a
fixed ``(C, M)`` -1-padded matrix, so the probe→gather→rerank program
has static shapes and compiles once per batch bucket.  Small catalogs
(below ``exact_threshold``) fall back to the exact scorer
(:func:`fedrec_tpu.serve.build_recommend_fn`) — two-stage only pays past
the scale where the full matmul stops being cheap, and the fallback is
parity-tested against the dense path.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from fedrec_tpu.models import NewsRecommender
from fedrec_tpu.serve import build_recommend_fn

_NEG = jnp.finfo(jnp.float32).min


def kmeans(
    vecs: jnp.ndarray,
    num_clusters: int,
    iters: int = 10,
    seed: int = 0,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Lloyd's k-means over (N, D) vectors, jitted end-to-end.

    Returns ``(centroids (C, D) float32, assign (N,) int32)``.  Init is a
    seeded no-replacement row sample; empty clusters keep their previous
    centroid (standard Lloyd's degeneracy handling — they can re-acquire
    members as other centroids move).  Assignment uses the dot-product
    expansion ``argmin ||x-c||^2 = argmin (||c||^2/2 - x.c)`` so the inner
    loop is one MXU matmul, not an (N, C, D) difference tensor.
    """
    vecs = jnp.asarray(vecs, jnp.float32)
    n = vecs.shape[0]
    num_clusters = min(int(num_clusters), n)
    init = vecs[jax.random.choice(
        jax.random.PRNGKey(seed), n, (num_clusters,), replace=False
    )]

    def assign_to(cents, vecs):
        half_sq = 0.5 * jnp.sum(cents * cents, axis=1)              # (C,)
        return jnp.argmin(half_sq[None, :] - vecs @ cents.T, axis=1)

    @jax.jit
    def run(vecs, cents):
        def step(cents, _):
            assign = assign_to(cents, vecs)
            sums = jax.ops.segment_sum(vecs, assign, num_segments=num_clusters)
            counts = jax.ops.segment_sum(
                jnp.ones((n,), jnp.float32), assign, num_segments=num_clusters
            )
            new = jnp.where(
                counts[:, None] > 0, sums / jnp.maximum(counts, 1.0)[:, None], cents
            )
            return new, None
        cents, _ = lax.scan(step, cents, None, length=iters)
        # assignment recomputed against the FINAL centroids: the scan's last
        # per-step assignment predates the last centroid update, and a
        # member table inconsistent with the probing centroids silently
        # costs recall
        return cents, assign_to(cents, vecs).astype(jnp.int32)

    return run(vecs, init)


@dataclass(frozen=True)
class TwoStageIndex:
    """Immutable per-generation retrieval structure.

    ``exact=True`` means "no coarse stage" — the catalog is small enough
    that the full matmul wins; ``centroids``/``members`` are None then.
    ``members`` is the (C, M) cluster-membership matrix, -1-padded to the
    largest cluster's size: fixed shapes for the jitted gather, at the
    cost of gathering (and masking) padding for skewed clusterings.
    """

    news_vecs: Any                    # (N, D)
    valid_mask: np.ndarray | None     # (N,) bool
    exact: bool
    centroids: Any = None             # (C, D) float32
    members: Any = None               # (C, M) int32, -1-padded
    n_probe: int = 0

    @property
    def num_news(self) -> int:
        return int(self.news_vecs.shape[0])

    def stats(self) -> dict:
        if self.exact:
            return {"exact": True, "num_news": self.num_news}
        c, m = self.members.shape
        scanned = min(self.n_probe, c) * m
        return {
            "exact": False,
            "num_news": self.num_news,
            "num_clusters": int(c),
            "max_cluster_size": int(m),
            "n_probe": int(self.n_probe),
            # worst-case fraction of the catalog touched per request
            "scan_fraction": round(scanned / max(self.num_news, 1), 4),
        }


def build_index(
    news_vecs,
    num_clusters: int = 0,
    n_probe: int = 8,
    iters: int = 10,
    seed: int = 0,
    valid_mask: np.ndarray | None = None,
    exact_threshold: int = 4096,
) -> TwoStageIndex:
    """Build the per-generation index.  ``num_clusters <= 1`` or a catalog
    at/below ``exact_threshold`` selects the exact path — the coarse stage
    only pays once the full matmul stops being the cheap option."""
    news_vecs = jnp.asarray(news_vecs)
    n = news_vecs.shape[0]
    if num_clusters <= 1 or n <= exact_threshold:
        return TwoStageIndex(news_vecs=news_vecs, valid_mask=valid_mask, exact=True)

    cents, assign = kmeans(news_vecs, num_clusters, iters=iters, seed=seed)
    assign = np.asarray(assign)
    num_clusters = int(cents.shape[0])
    # membership lists on the host (one-time build), -1-padded to the max
    # cluster size; id 0 (pad slot) and invalid rows never become
    # candidates at all — cheaper than masking them per request
    ids = np.arange(n)
    keep = ids != 0
    if valid_mask is not None:
        keep &= np.asarray(valid_mask, bool)
    buckets = [ids[(assign == c) & keep] for c in range(num_clusters)]
    m = max(1, max(len(b) for b in buckets))
    members = np.full((num_clusters, m), -1, np.int32)
    for c, b in enumerate(buckets):
        members[c, : len(b)] = b
    return TwoStageIndex(
        news_vecs=news_vecs,
        valid_mask=valid_mask,
        exact=False,
        centroids=cents,
        members=jnp.asarray(members),
        n_probe=int(n_probe),
    )


def build_two_stage_fn(
    model: NewsRecommender,
    index: TwoStageIndex,
    top_k: int = 10,
    exclude_history: bool = True,
) -> Callable:
    """Compile ``retrieve(user_params, history) -> (ids, scores)`` over a
    bound index — the :func:`fedrec_tpu.serve.build_recommend_fn` contract
    minus the table argument (the index owns its generation's table).

    Exact indexes delegate to the dense scorer (bit-identical fallback);
    two-stage ones run probe -> fixed-shape member gather -> exact rerank.
    Tail slots past the valid candidates carry id -1 and the sentinel
    score, exactly like the dense path.
    """
    if index.exact:
        base = build_recommend_fn(
            model,
            top_k=top_k,
            exclude_history=exclude_history,
            valid_mask=index.valid_mask,
        )
        table = index.news_vecs

        def retrieve_exact(user_params, history):
            return base(user_params, table, history)

        return retrieve_exact

    news_vecs, centroids, members = index.news_vecs, index.centroids, index.members
    n = news_vecs.shape[0]
    n_probe = min(index.n_probe, members.shape[0])
    k = min(top_k, n_probe * members.shape[1])

    @jax.jit
    def retrieve(user_params, history):
        # same explicit clamp as both scorers in fedrec_tpu.serve: degenerate
        # ids must behave identically on the exact and two-stage paths
        his_vecs = news_vecs[jnp.clip(history, 0, n - 1)]
        user_vec = model.apply(
            {"params": {"user_encoder": user_params}},
            his_vecs,
            method=NewsRecommender.encode_user,
        ).astype(jnp.float32)                                   # (B, D)
        b = history.shape[0]
        _, top_c = lax.top_k(user_vec @ centroids.T, n_probe)   # (B, n_probe)
        cand_ids = members[top_c].reshape(b, -1)                # (B, n_probe*M)
        safe = jnp.clip(cand_ids, 0, n - 1)
        cand_vecs = news_vecs[safe].astype(jnp.float32)         # (B, cand, D)
        scores = jnp.einsum("bd,bcd->bc", user_vec, cand_vecs)
        invalid = cand_ids < 0                                  # member padding
        if exclude_history:
            # clusters partition the ids, so candidates never repeat across
            # probes; membership test against the (small) history is a
            # (B, cand, H) broadcast compare
            invalid = invalid | (
                cand_ids[:, :, None] == history[:, None, :]
            ).any(-1)
        scores = jnp.where(invalid, _NEG, scores)
        top_scores, pick = lax.top_k(scores, k)
        top_ids = jnp.take_along_axis(cand_ids, pick, axis=1)
        top_ids = jnp.where(top_scores <= _NEG, -1, top_ids)
        return top_ids.astype(jnp.int32), top_scores

    return retrieve


def recall_at_k(
    model: NewsRecommender,
    index: TwoStageIndex,
    user_params,
    histories,
    k: int = 10,
    exclude_history: bool = True,
) -> float:
    """Measured (not assumed) recall@k of the two-stage path vs brute
    force on the SAME generation: mean over queries of
    ``|approx top-k ∩ exact top-k| / |exact top-k|``."""
    exact = build_recommend_fn(
        model, top_k=k, exclude_history=exclude_history, valid_mask=index.valid_mask
    )
    approx = build_two_stage_fn(
        model, index, top_k=k, exclude_history=exclude_history
    )
    histories = jnp.asarray(histories, jnp.int32)
    ids_e = np.asarray(exact(user_params, index.news_vecs, histories)[0])
    ids_a = np.asarray(approx(user_params, histories)[0])
    hits, total = 0, 0
    for row_e, row_a in zip(ids_e, ids_a):
        truth = set(int(i) for i in row_e if i >= 0)
        if not truth:
            continue
        got = set(int(i) for i in row_a if i >= 0)
        hits += len(truth & got)
        total += len(truth)
    return hits / total if total else 1.0
