"""Deadline-driven asyncio micro-batcher with fixed padded batch shapes.

Online recommendation traffic arrives one user at a time, but the jitted
scorer is a batch program whose compile cache is keyed on shape: feed it
every arrival count from 1..128 and XLA recompiles up to 128 variants —
each a multi-second stall at serving time.  The batcher therefore
coalesces pending requests and pads them up to the SMALLEST of a few
fixed bucket sizes (default 1/8/32/128), so the scorer only ever sees
``len(batch_sizes)`` shapes, all compiled during warmup.

Flush policy (deadline-driven, not size-driven):

* a batch flushes as soon as the largest bucket is full, OR
* when the OLDEST pending request has waited ``flush_ms`` (bounded added
  latency even at 1 req/s), OR
* when any pending request's own deadline is about to expire — a request
  with 3 ms of slack left must not sit out a 5 ms coalescing window.

Backpressure is queue-depth based and immediate: past ``max_queue``
pending requests, ``submit`` raises :class:`Backpressure` instead of
growing an unbounded queue whose tail would all miss their deadlines
anyway (fail fast at admission, the load-shedding edge every
deadline-driven server needs).

Each response reports the batch it rode in (bucket size + occupancy) and
an honest ``deadline_met`` flag computed AFTER scoring — a served-late
response says so rather than pretending.

The scorer callable runs synchronously on the event loop.  That is
deliberate: on one host the scorer is the bottleneck resource, and
running it inline makes batch formation self-clocking — while one batch
computes, the next batch's requests pile up, so occupancy rises with
load (the classic adaptive-batching property) with zero tuning.
"""

from __future__ import annotations

import asyncio
import time
from dataclasses import dataclass
from typing import Callable, Sequence

import numpy as np

from fedrec_tpu.obs import get_registry, get_tracer


class Backpressure(RuntimeError):
    """Queue depth exceeded ``max_queue``; request rejected at admission."""


@dataclass
class ServedResult:
    """Per-request outcome: top-k ids/scores plus serving telemetry."""

    ids: np.ndarray          # (k,) int32, -1-padded past the valid items
    scores: np.ndarray       # (k,) float32
    generation: int          # embedding-store generation that scored it
    deadline_met: bool       # finish time vs the request's own deadline
    latency_ms: float        # enqueue -> results distributed
    batch_size: int          # bucket the request rode in
    occupancy: float         # real requests / bucket size


@dataclass
class _Pending:
    history: np.ndarray      # (H,) int32, already padded/truncated
    deadline: float | None   # absolute monotonic time, None = no deadline
    enqueued: float
    future: asyncio.Future


class MicroBatcher:
    """Coalesce ``submit()`` calls into fixed-shape scored batches.

    ``score_batch(hist: (B, H) int32 ndarray) -> (ids (B, k), scores (B, k),
    generation)`` — B is always one of ``batch_sizes``.  Rows past the real
    request count are zero-padded and their outputs discarded.
    """

    def __init__(
        self,
        score_batch: Callable,
        history_len: int,
        batch_sizes: Sequence[int] = (1, 8, 32, 128),
        flush_ms: float = 2.0,
        max_queue: int = 1024,
        deadline_margin_ms: float = 1.0,
        clock: Callable[[], float] = time.monotonic,
        registry=None,
        tracer=None,
    ):
        if not batch_sizes or list(batch_sizes) != sorted(set(batch_sizes)):
            raise ValueError("batch_sizes must be sorted, unique, non-empty")
        self._score = score_batch
        self.history_len = int(history_len)
        self.batch_sizes = tuple(int(b) for b in batch_sizes)
        self.flush_s = flush_ms / 1e3
        self.deadline_margin_s = deadline_margin_ms / 1e3
        self.max_queue = int(max_queue)
        self._clock = clock
        self._queue: list[_Pending] = []
        self._wake = asyncio.Event()
        self._task: asyncio.Task | None = None
        self._running = False
        # ---- metrics. The plain attributes stay the source of truth for
        # the wire `metrics()` dict (backward-compat keys); the registry
        # instruments mirror them for snapshots/Prometheus, plus the
        # latency histogram only the registry can hold.
        self.served = 0
        self.rejected = 0
        self.deadline_missed = 0
        self.batches_by_size: dict[int, int] = {b: 0 for b in self.batch_sizes}
        self._occupancy_sum = 0.0
        self._batches = 0
        reg = registry or get_registry()
        self.tracer = tracer or get_tracer()
        self._m_served = reg.counter("serve.requests_total", "requests served")
        self._m_rejected = reg.counter(
            "serve.rejected_total", "requests shed at admission (backpressure)"
        )
        self._m_missed = reg.counter(
            "serve.deadline_missed_total", "responses served past their deadline"
        )
        self._m_batches = reg.counter(
            "serve.batches_total", "batches flushed", labels=("bucket",)
        )
        self._m_qdepth = reg.gauge("serve.queue_depth", "pending requests")
        self._m_latency = reg.histogram(
            "serve.latency_ms", "request latency, enqueue -> results (ms)"
        )

    # ------------------------------------------------------------ lifecycle
    async def start(self) -> None:
        if self._task is None:
            self._running = True
            self._task = asyncio.ensure_future(self._run())

    async def stop(self) -> None:
        self._running = False
        self._wake.set()
        if self._task is not None:
            try:
                await self._task
            except asyncio.CancelledError:
                # interpreter shutdown cancels tasks out from under us; the
                # queue drain below must still run so callers fail cleanly
                pass
            self._task = None
        for p in self._queue:  # drain: fail cleanly rather than hang callers
            if not p.future.done():
                p.future.set_exception(RuntimeError("batcher stopped"))
        self._queue.clear()

    # ------------------------------------------------------------ submit
    def _normalize(self, history) -> np.ndarray:
        """Most recent ``history_len`` clicks, zero-padded at the tail —
        the training batcher's layout, so the user encoder sees the same
        distribution it was trained on."""
        h = np.asarray(list(history)[-self.history_len:], np.int32)
        out = np.zeros(self.history_len, np.int32)
        out[: h.shape[0]] = h
        return out

    async def submit(self, history, deadline_ms: float | None = None) -> ServedResult:
        if self._task is None:
            raise RuntimeError("batcher not started")
        if len(self._queue) >= self.max_queue:
            self.rejected += 1
            self._m_rejected.inc()
            raise Backpressure(
                f"queue depth {len(self._queue)} >= max_queue {self.max_queue}"
            )
        now = self._clock()
        pending = _Pending(
            history=self._normalize(history),
            deadline=None if deadline_ms is None else now + deadline_ms / 1e3,
            enqueued=now,
            future=asyncio.get_running_loop().create_future(),
        )
        self._queue.append(pending)
        self._m_qdepth.set(len(self._queue))
        self._wake.set()
        return await pending.future

    # ------------------------------------------------------------ flush loop
    def _flush_at(self) -> float:
        """Earliest moment any pending request forces a flush."""
        oldest = min(p.enqueued for p in self._queue)
        at = oldest + self.flush_s
        for p in self._queue:
            if p.deadline is not None:
                at = min(at, p.deadline - self.deadline_margin_s)
        return at

    async def _run(self) -> None:
        while self._running:
            if not self._queue:
                self._wake.clear()
                await self._wake.wait()
                continue
            now = self._clock()
            flush_at = self._flush_at()
            if len(self._queue) >= self.batch_sizes[-1] or now >= flush_at:
                self._flush_one()
                # yield so submitters queued behind the (synchronous) scorer
                # get scheduled before the next flush decision
                await asyncio.sleep(0)
                continue
            self._wake.clear()
            try:
                await asyncio.wait_for(self._wake.wait(), flush_at - now)
            except (asyncio.TimeoutError, TimeoutError):
                pass

    def _flush_one(self) -> None:
        take = min(len(self._queue), self.batch_sizes[-1])
        batch, self._queue = self._queue[:take], self._queue[take:]
        self._m_qdepth.set(len(self._queue))
        bucket = next(b for b in self.batch_sizes if b >= take)
        # request lifecycle spans (enqueue -> batch -> dispatch -> reply):
        # the coalescing window ends here; its length is stamped from the
        # batcher clock, only the duration crosses to the tracer clock
        self.tracer.add_span(
            "serve.queue_wait",
            dur_s=self._clock() - min(p.enqueued for p in batch),
            bucket=bucket, n=take,
        )
        hist = np.zeros((bucket, self.history_len), np.int32)
        for i, p in enumerate(batch):
            hist[i] = p.history
        try:
            with self.tracer.span("serve.dispatch", bucket=bucket, n=take):
                ids, scores, generation = self._score(hist)
        except Exception as e:  # noqa: BLE001 — fail the batch, not the server
            for p in batch:
                if not p.future.done():
                    p.future.set_exception(e)
            return
        ids = np.asarray(ids)
        scores = np.asarray(scores)
        done = self._clock()
        self._batches += 1
        self.batches_by_size[bucket] += 1
        self._m_batches.inc(bucket=bucket)
        self._occupancy_sum += take / bucket
        with self.tracer.span("serve.reply", bucket=bucket, n=take):
            for i, p in enumerate(batch):
                met = p.deadline is None or done <= p.deadline
                if not met:
                    self.deadline_missed += 1
                    self._m_missed.inc()
                self.served += 1
                self._m_served.inc()
                latency_ms = (done - p.enqueued) * 1e3
                self._m_latency.observe(latency_ms)
                self.tracer.add_span(
                    "serve.request", dur_s=done - p.enqueued, bucket=bucket
                )
                if not p.future.done():  # caller may have been cancelled
                    p.future.set_result(
                        ServedResult(
                            ids=ids[i],
                            scores=scores[i],
                            generation=int(generation),
                            deadline_met=met,
                            latency_ms=latency_ms,
                            batch_size=bucket,
                            occupancy=take / bucket,
                        )
                    )

    # ------------------------------------------------------------ metrics
    def metrics(self) -> dict:
        return {
            "served": self.served,
            "rejected": self.rejected,
            "deadline_missed": self.deadline_missed,
            "batches": self._batches,
            "batches_by_size": dict(self.batches_by_size),
            "mean_occupancy": round(self._occupancy_sum / self._batches, 4)
            if self._batches
            else None,
            "queue_depth": len(self._queue),
        }
