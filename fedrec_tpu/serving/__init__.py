"""Online serving subsystem: micro-batched request serving over a
versioned embedding store with two-stage retrieval.

The training side of this repo ends at a jitted batch scorer
(:mod:`fedrec_tpu.serve`) and a one-shot CLI
(:mod:`fedrec_tpu.cli.recommend`).  This package turns that into a
long-lived online service:

* :mod:`fedrec_tpu.serving.store` — versioned news-embedding/user-param
  generations with atomic hot-swap, so serving tracks the federated
  trainer round-by-round without a restart;
* :mod:`fedrec_tpu.serving.batcher` — asyncio deadline-driven
  micro-batcher that coalesces single-user requests into a few fixed
  padded batch shapes (the jitted scorer never recompiles under load);
* :mod:`fedrec_tpu.serving.retrieval` — two-stage retrieval (JAX k-means
  coarse quantizer + exact rerank) for catalogs past the
  full-matmul-per-request scale, with an exact-path fallback
  parity-tested against :func:`fedrec_tpu.serve.build_recommend_fn`;
* :mod:`fedrec_tpu.serving.server` — the TCP/JSON-lines service wiring
  batcher -> store -> retrieval, with latency/occupancy/swap metrics.
"""

from fedrec_tpu.serving.batcher import Backpressure, MicroBatcher, ServedResult
from fedrec_tpu.serving.client import (
    ServingClient,
    ServingClientPool,
    ServingUnavailable,
)
from fedrec_tpu.serving.retrieval import (
    TwoStageIndex,
    build_index,
    build_two_stage_fn,
    kmeans,
    recall_at_k,
)
from fedrec_tpu.serving.server import ServingService, serve_forever, start_server
from fedrec_tpu.serving.store import EmbeddingStore, EmptyStoreError, Generation

__all__ = [
    "Backpressure",
    "EmbeddingStore",
    "EmptyStoreError",
    "Generation",
    "MicroBatcher",
    "ServedResult",
    "ServingClient",
    "ServingClientPool",
    "ServingService",
    "ServingUnavailable",
    "TwoStageIndex",
    "build_index",
    "build_two_stage_fn",
    "kmeans",
    "recall_at_k",
    "serve_forever",
    "start_server",
]
