"""Renyi-DP accountant for the subsampled Gaussian mechanism.

Replaces the reference's Opacus dependency, which it used *only* to derive a
noise multiplier sigma from (epsilon, delta, epochs) — the wrapped model,
optimizer and loader were discarded (reference ``client.py:271-281``; the
report admits no clipping was performed). Here the accountant is native and
the training loop actually clips.

Math (Mironov 2017, "Renyi Differential Privacy"; Mironov-Talwar-Zhang 2019,
"Renyi Differential Privacy of the Sampled Gaussian Mechanism"):

  * Gaussian mechanism with noise multiplier sigma at integer Renyi order
    alpha: RDP(alpha) = alpha / (2 sigma^2).
  * Poisson-subsampled Gaussian with sampling rate q, integer alpha:
      RDP(alpha) <= 1/(alpha-1) * log( sum_{k=0..alpha}
          C(alpha,k) (1-q)^(alpha-k) q^k exp((k^2 - k) / (2 sigma^2)) )
    computed in log space for stability.
  * Composition over T steps adds RDP linearly.
  * Conversion to (epsilon, delta)-DP uses the improved bound
    (Balle et al. 2020 as used by Opacus/TF-privacy):
      eps = rdp - (log(delta) + log(alpha)) / (alpha - 1) + log1p(-1/alpha)
    minimized over orders.

``calibrate_sigma`` binary-searches sigma for a target epsilon — the native
equivalent of ``PrivacyEngine.make_private_with_epsilon(...)``'s noise
calibration (reference ``client.py:271-281``, with C=2, delta=1e-5, EPOCHS=50
defaults from ``client.py:220-224``).
"""

from __future__ import annotations

import math

import numpy as np

DEFAULT_ORDERS = tuple(range(2, 65)) + (80, 96, 128, 256, 512)


def _log_binom(n: int, k: int) -> float:
    return math.lgamma(n + 1) - math.lgamma(k + 1) - math.lgamma(n - k + 1)


def compute_rdp_subsampled_gaussian(
    q: float, sigma: float, steps: int, orders: tuple[int, ...] = DEFAULT_ORDERS
) -> np.ndarray:
    """Total RDP at each integer order after ``steps`` compositions."""
    if sigma <= 0:
        raise ValueError("sigma must be positive")
    if not 0 < q <= 1:
        raise ValueError("sampling rate q must be in (0, 1]")
    rdp = np.zeros(len(orders))
    for i, alpha in enumerate(orders):
        if q == 1.0:
            rdp[i] = alpha / (2 * sigma**2)
        else:
            # log-sum-exp over the binomial expansion
            log_terms = [
                _log_binom(alpha, k)
                + (alpha - k) * math.log1p(-q)
                + (k * math.log(q) if k > 0 else 0.0)
                + (k * k - k) / (2 * sigma**2)
                for k in range(alpha + 1)
            ]
            m = max(log_terms)
            log_sum = m + math.log(sum(math.exp(t - m) for t in log_terms))
            rdp[i] = log_sum / (alpha - 1)
    return rdp * steps


def compute_epsilon(
    q: float,
    sigma: float,
    steps: int,
    delta: float,
    orders: tuple[int, ...] = DEFAULT_ORDERS,
) -> float:
    """(epsilon, delta)-DP guarantee after ``steps`` subsampled-Gaussian steps."""
    if not 0 < delta < 1:
        raise ValueError("delta must be in (0, 1)")
    rdp = compute_rdp_subsampled_gaussian(q, sigma, steps, orders)
    eps = np.array(
        [
            r - (math.log(delta) + math.log(a)) / (a - 1) + math.log1p(-1.0 / a)
            for r, a in zip(rdp, orders)
        ]
    )
    return float(np.min(eps))


def calibrate_sigma(
    target_epsilon: float,
    delta: float,
    sample_rate: float,
    steps: int,
    orders: tuple[int, ...] = DEFAULT_ORDERS,
    sigma_min: float = 1e-2,
    sigma_max: float = 1e4,
    tol: float = 1e-4,
) -> float:
    """Smallest sigma achieving ``epsilon <= target_epsilon`` at ``delta``.

    Native replacement for Opacus' ``get_noise_multiplier`` path inside
    ``make_private_with_epsilon`` (reference ``client.py:271-281``).
    """
    if target_epsilon <= 0:
        raise ValueError("target_epsilon must be positive")
    if compute_epsilon(sample_rate, sigma_max, steps, delta, orders) > target_epsilon:
        raise ValueError("target_epsilon unattainable even at sigma_max")
    lo, hi = sigma_min, sigma_max
    # ensure lo is infeasible (eps too big) so the invariant holds
    if compute_epsilon(sample_rate, lo, steps, delta, orders) <= target_epsilon:
        return lo
    while hi - lo > tol * max(1.0, lo):
        mid = 0.5 * (lo + hi)
        if compute_epsilon(sample_rate, mid, steps, delta, orders) <= target_epsilon:
            hi = mid
        else:
            lo = mid
    return hi


def sampling_profile(cfg, n_train: int) -> tuple[float, int]:
    """The ONE definition of ``(q, steps_per_epoch)`` for the accountant —
    calibration and the spend-side schedule must agree or the budget
    silently diverges.

    Fixed-world (no ``fed.population``): every client trains every round,
    so the only subsampling is the batch draw — ``q = B / per_client``
    with ``per_client = n_train // fed.num_clients``.

    Sampled-world (``fed.population.num_clients`` above the slot count):
    privacy is amplified TWICE — a client's data only enters a round when
    the client is sampled (``q_client = slots / population``, the
    per-round cohort fraction; over-selected spares never exceed the slot
    count so this bounds the participating fraction), and within a
    selected round only a batch of its shard is touched
    (``q_batch = B / shard_size``). The two Poisson subsamplings compose
    multiplicatively: ``q = q_client * q_batch`` — accounting each step at
    the batch-level constant alone would overstate the privacy spend of a
    sampled run by the full cohort fraction. Step counts are per SELECTED
    round: ``shard_size // B`` steps per local epoch.
    """
    n_train = max(int(n_train), 1)
    pop = getattr(cfg.fed, "population", None)
    pop_n = int(getattr(pop, "num_clients", 0) or 0)
    if pop_n > cfg.fed.num_clients:
        if getattr(pop, "sampler", "uniform") != "uniform":
            raise ValueError(
                "privacy amplification-by-subsampling assumes a UNIFORM "
                f"cohort draw; fed.population.sampler={pop.sampler!r} "
                "biases per-client selection probability, so q = slots/"
                "population would understate epsilon for over-selected "
                "clients. Use sampler=uniform when privacy is enabled."
            )
        shard = max(n_train // pop_n, 1)
        q_batch = min(1.0, cfg.data.batch_size / shard)
        q_client = cfg.fed.num_clients / pop_n
        q = min(1.0, q_client * q_batch)
        steps_per_epoch = max(shard // cfg.data.batch_size, 1)
        return q, steps_per_epoch
    per_client = max(n_train // cfg.fed.num_clients, 1)
    q = min(1.0, cfg.data.batch_size / per_client)
    steps_per_epoch = max(per_client // cfg.data.batch_size, 1)
    return q, steps_per_epoch


def round_epsilon_schedule(cfg, n_train: int):
    """``rounds_done -> epsilon`` for the run's actual step cadence.

    The spend side of the accountant: where :func:`calibrate_from_config`
    answers "what sigma meets the budget", this answers "how much of the
    (epsilon, delta) budget has round k consumed" — the number the
    Trainer publishes as the ``privacy.epsilon_spent`` gauge each round
    (docs/OBSERVABILITY.md).  Same ``q`` and steps-per-epoch definitions
    as calibration, so the trajectory's final value is comparable to the
    configured target.  Requires ``cfg.privacy.sigma`` > 0 (calibrated
    or explicit); results are memoized — one accountant evaluation per
    new round, not per metric snapshot.
    """
    sigma = cfg.privacy.sigma
    if sigma <= 0:
        raise ValueError(
            "privacy.sigma not set; calibrate it (calibrate_from_config) "
            "before asking for a spent-epsilon schedule"
        )
    q, steps_per_epoch = sampling_profile(cfg, n_train)
    steps_per_round = steps_per_epoch * cfg.fed.local_epochs
    delta = cfg.privacy.delta
    cache: dict[int, float] = {}

    def spent(rounds_done: int) -> float:
        rounds_done = int(rounds_done)
        if rounds_done <= 0:
            return 0.0
        if rounds_done not in cache:
            cache[rounds_done] = compute_epsilon(
                q, sigma, steps_per_round * rounds_done, delta
            )
        return cache[rounds_done]

    return spent


def calibrate_from_config(cfg, n_train: int) -> float:
    """Sigma for ``cfg.privacy`` given the total training-sample count.

    One shared definition of the sample rate ``q`` and accountant step
    count (:func:`sampling_profile` — population-aware: client sampling
    amplifies ``q`` by the per-round cohort fraction) — the CLI drivers
    and the accuracy loop must agree or their privacy budgets silently
    diverge.
    """
    q, steps_per_epoch = sampling_profile(cfg, n_train)
    return calibrate_sigma(
        cfg.privacy.epsilon,
        cfg.privacy.delta,
        q,
        steps_per_epoch * cfg.privacy.accountant_epochs,
    )
