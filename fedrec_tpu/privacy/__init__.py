from fedrec_tpu.privacy.accountant import (
    calibrate_from_config,
    calibrate_sigma,
    compute_epsilon,
    compute_rdp_subsampled_gaussian,
    round_epsilon_schedule,
    sampling_profile,
)
from fedrec_tpu.privacy.dpsgd import (
    clip_by_global_norm_per_example,
    make_ldp_news_noise_fn,
    make_noise_fn,
    per_example_clipped_grads,
)

__all__ = [
    "calibrate_from_config",
    "calibrate_sigma",
    "clip_by_global_norm_per_example",
    "compute_epsilon",
    "compute_rdp_subsampled_gaussian",
    "make_ldp_news_noise_fn",
    "make_noise_fn",
    "per_example_clipped_grads",
    "round_epsilon_schedule",
    "sampling_profile",
]
