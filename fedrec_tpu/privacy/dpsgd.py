"""DP-SGD primitives: per-example clipping + device-side Gaussian noise.

Two mechanisms, both drawing noise on-device from the per-client PRNG key
*before* any cross-client collective (local-DP semantics):

  * ``dpsgd`` — the honest mechanism the reference intended: per-example
    gradients (``jax.vmap`` of ``jax.grad``), clip each example's global norm
    to C, average, add N(0, (sigma C / B)^2). The reference instantiated
    Opacus for exactly this and then discarded the wrapped model, performing
    no clipping at all (reference ``client.py:271-281``; Final_Report.pdf
    section VI.A.4 "I have not done gradient clipping").
  * ``ldp_news`` — reference behavioral parity: unclipped Gaussian noise
    added only to the news-embedding gradients (reference ``client.py:87-89``,
    which also noises nothing in the user tower and had a shape bug on the
    history noise — fixed here by construction).
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp

from fedrec_tpu.config import PrivacyConfig


def _global_norm(tree: Any) -> jnp.ndarray:
    leaves = jax.tree_util.tree_leaves(tree)
    return jnp.sqrt(sum(jnp.sum(jnp.square(x)) for x in leaves))


def per_example_global_norms(per_example_grads: Any) -> jnp.ndarray:
    """(B,) global gradient norm per example — the quantity DP-SGD clips
    against, and the one the health sentry's clip-rate is defined over."""
    return jnp.sqrt(
        sum(
            jnp.sum(jnp.square(x), axis=tuple(range(1, x.ndim)))
            for x in jax.tree_util.tree_leaves(per_example_grads)
        )
    )


def _apply_clip(per_example_grads: Any, norms: jnp.ndarray, clip_norm: float) -> Any:
    """THE clip body: scale each example's pytree so its global norm is
    <= clip_norm, given precomputed per-example norms — shared by the
    standalone helper and the DP-SGD estimator so the clipping epsilon
    and broadcast can never diverge between them."""
    scale = jnp.minimum(1.0, clip_norm / jnp.maximum(norms, 1e-12))  # (B,)
    return jax.tree_util.tree_map(
        lambda x: x * scale.reshape((-1,) + (1,) * (x.ndim - 1)), per_example_grads
    )


def clip_by_global_norm_per_example(per_example_grads: Any, clip_norm: float) -> Any:
    """Scale each example's gradient pytree to global norm <= clip_norm.

    ``per_example_grads`` leaves have a leading batch axis.
    """
    norms = per_example_global_norms(per_example_grads)  # (B,)
    return _apply_clip(per_example_grads, norms, clip_norm)


def add_gaussian_noise(tree: Any, rng: jax.Array, std: float | jnp.ndarray) -> Any:
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    keys = jax.random.split(rng, len(leaves))
    noised = [
        leaf + std * jax.random.normal(k, leaf.shape, leaf.dtype)
        for leaf, k in zip(leaves, keys)
    ]
    return jax.tree_util.tree_unflatten(treedef, noised)


def per_example_clipped_grads(
    per_example_loss_fn: Callable[..., jnp.ndarray],
    params: Any,
    batch_args: tuple,
    clip_norm: float,
    with_stats: bool = False,
) -> tuple:
    """Mean of per-example clipped gradients (the DP-SGD estimator).

    ``per_example_loss_fn(params, *example_args) -> scalar`` is vmapped over
    the leading axis of every element of ``batch_args``. Returns
    ``(mean_loss, mean_clipped_grads)``; noise is the caller's job (it needs
    the PRNG and the B divisor).

    ``with_stats=True`` appends a clipping-stats dict — ``clip_rate``
    (fraction of the batch whose pre-clip global norm strictly exceeded
    C, i.e. whose gradient was actually scaled) and ``max_norm`` of the
    pre-clip norms — the health sentry's DP observability surface (an
    all-clipped batch means C is strangling the signal; a never-clipped
    one means C buys no sensitivity bound).
    """
    grad_fn = jax.vmap(
        jax.value_and_grad(per_example_loss_fn),
        in_axes=(None,) + (0,) * len(batch_args),
    )
    losses, grads = grad_fn(params, *batch_args)
    norms = per_example_global_norms(grads)  # (B,)
    clipped = _apply_clip(grads, norms, clip_norm)
    mean_grads = jax.tree_util.tree_map(lambda x: jnp.mean(x, axis=0), clipped)
    if not with_stats:
        return jnp.mean(losses), mean_grads
    stats = {
        "clip_rate": jnp.mean((norms > clip_norm).astype(jnp.float32)),
        "max_norm": jnp.max(norms),
    }
    return jnp.mean(losses), mean_grads, stats


def make_noise_fn(privacy: PrivacyConfig, batch_size: int) -> Callable | None:
    """LDP noise hook for the train step (mechanism-agnostic signature).

    Returns ``noise_fn(grads_tuple, rng) -> grads_tuple`` or None when
    privacy is disabled. For ``dpsgd`` the std is sigma * C / B (noise on the
    *mean* of B clipped per-example grads); for ``ldp_news`` it is raw sigma
    on the news-embedding grads only (reference ``client.py:87-89`` adds
    ``N(0, sigma^2)`` with no clipping — the tuple's first element, the
    user-tower grads, passes through untouched for parity).
    """
    if not privacy.enabled:
        return None
    sigma = privacy.sigma
    if sigma <= 0:
        raise ValueError(
            "privacy.sigma not set; calibrate with fedrec_tpu.privacy.calibrate_sigma"
        )
    if privacy.mechanism == "dpsgd":
        std = sigma * privacy.clip_norm / batch_size

        def noise_fn(grads: tuple, rng: jax.Array) -> tuple:
            keys = jax.random.split(rng, len(grads))
            return tuple(add_gaussian_noise(g, k, std) for g, k in zip(grads, keys))

        return noise_fn

    if privacy.mechanism == "ldp_news":

        def noise_fn(grads: tuple, rng: jax.Array) -> tuple:
            user_g, *news_parts = grads
            keys = jax.random.split(rng, len(news_parts))
            noised = [
                add_gaussian_noise(g, k, sigma) for g, k in zip(news_parts, keys)
            ]
            return (user_g, *noised)

        return noise_fn

    raise ValueError(f"unknown privacy mechanism {privacy.mechanism!r}")


def make_ldp_news_noise_fn(sigma: float) -> Callable:
    """Convenience: reference-parity news-grad noise with explicit sigma."""
    cfg = PrivacyConfig(enabled=True, sigma=sigma, mechanism="ldp_news")
    return make_noise_fn(cfg, batch_size=1)
