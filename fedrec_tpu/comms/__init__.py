"""Communication-efficient cross-host sync: the update-compression codec
subsystem (``fed.dcn_compress``). See :mod:`fedrec_tpu.comms.codecs`."""

from fedrec_tpu.comms.codecs import (
    CODECS,
    EF_CODECS,
    CodecState,
    EncodedTree,
    codec_decodes_per_contribution,
    codec_state_bytes,
    codec_uses_feedback,
    decode_gathered,
    decode_leaf,
    decode_tree,
    encode_leaf,
    encode_tree,
    jax_encode_decode,
    load_codec_state,
    payload_nbytes,
    topk_count,
    tree_dense_nbytes,
    validate_codec,
)

__all__ = [
    "CODECS",
    "EF_CODECS",
    "CodecState",
    "EncodedTree",
    "codec_decodes_per_contribution",
    "codec_state_bytes",
    "codec_uses_feedback",
    "decode_gathered",
    "decode_leaf",
    "decode_tree",
    "encode_leaf",
    "encode_tree",
    "jax_encode_decode",
    "load_codec_state",
    "payload_nbytes",
    "topk_count",
    "tree_dense_nbytes",
    "validate_codec",
]
