"""Update-compression codecs for the client→server sync.

The reference ships the FULL ``state_dict`` — frozen DistilBERT trunk
included — from every client every round over raw TCP (~268 MB/client/round,
Final_Report.pdf §VII.b). PR 1–6 already cut that to the two trainable
towers riding XLA collectives; this module is the next lever (ROADMAP open
item 3): compress the per-round *update* itself. One codec registry serves
both places an update crosses a wire (or a simulated one):

* the **in-graph round-end sync** (``train.step._make_local_sync``): each
  cohort client's round delta is encoded/decoded inside the jitted program
  (the jax variants below), modeling the cross-device uplink — sign1bit and
  topk carry **per-client error-feedback residuals** (a ``ClientState``
  field, spilled/restored through the ``fed.population`` sidecar store) so
  the mass a lossy codec drops re-enters the next round's update
  (EF-signSGD, Karimireddy et al. 2019; the communication-perspective FL
  survey, arXiv:2405.20431);
* the **coordinator's cross-host DCN gather**
  (``parallel.multihost.aggregate_from_hosts``): the numpy variants below
  encode each process's contribution into REAL wire buffers (what
  ``process_allgather`` actually ships), decode every contribution
  per-process before any reduction — so Byzantine-robust aggregators judge
  clients, not quantization noise (decode-before-reduce) — and the byte
  counts published to the metrics registry are measured from those buffers,
  not dtype arithmetic.

The numpy and jax variants implement the SAME arithmetic (same scales, same
round-half-to-even, same top-k tie-break: ties keep the lowest flat index),
pinned against each other in ``tests/test_comms.py``, so a trajectory
simulated in-graph matches what the wire codec would reconstruct.

Codecs:

``none``     — identity; the wire carries dense float32.
``int8``     — symmetric per-tensor int8: ``x ≈ q * scale`` with
               ``scale = max|x| / 127``; worst-case element error
               ``scale/2``. ~4× the wire. No residual (rounding noise is
               zero-mean and bounded).
``sign1bit`` — 1 bit per element + one f32 scale per tensor:
               ``x ≈ sign(x) * mean|x|`` (signSGD with majority-free
               scale). ~32× the wire. Biased — REQUIRES error feedback for
               convergence (``fed.dcn_error_feedback``).
``topk``     — structured sparsification: keep the ``ceil(ratio * n)``
               largest-|x| coordinates per tensor (index + value pairs).
               ``ratio = fed.dcn_topk_ratio``; ~``1/(2*ratio)``× the wire.
               Biased — requires error feedback.
``countsketch`` — LINEAR sketch: each tensor flattens into an
               ``m = ceil(width * n)`` bucket array via a seeded hash
               ``h : [n] -> [m]`` and sign ``s : [n] -> {±1}``
               (``y[h(i)] += s(i) * x[i]``); decode is ``x̂_i = s(i) *
               y[h(i)]`` — unbiased (``E[x̂] = x`` over the hash draw,
               colliding coordinates carry independent random signs),
               per-coordinate variance ~ ``(‖x‖² - x_i²)/m``. Because
               encode/decode are LINEAR maps sharing one seeded hash,
               ``decode(Σ encode(x_c)) == Σ x̂_c`` EXACTLY — a summing
               aggregation server (or the async buffer) can reduce
               sketches it cannot decode per contribution.
``randproj`` — LINEAR seeded random projection: the flat tensor is
               processed in 256-wide chunks, each projected by a shared
               ``±1/√d`` matrix ``R`` (``d = ceil(width * 256)``);
               decode is ``y @ Rᵀ`` — unbiased (``E[R Rᵀ] = I``),
               denser error than count-sketch (every coordinate takes a
               little noise) but no collision hot spots.

Both sketches decode AFTER the sum (arXiv 2405.20431's aggregated end of
the design space; the Smart-NIC wire-format constraint of arXiv
2307.06561): the wire only ever carries fixed-size linear images, so a
dumb summing device can do the reduce. The price: a per-contribution
decode does not exist once summed, so order statistics (trimmed mean /
median) cannot compose — the capability table below is where every
dispatch site learns that boundary.

DP ordering contract: per-example clipping and noise happen inside the
train step, *before* any encode ever sees the update — the codec compresses
an already-privatized delta, so the ε-accounting is untouched (pinned in
docs/DESIGN.md §5g).
"""

from __future__ import annotations

import io
from dataclasses import dataclass
from typing import Any

import numpy as np

CODECS = ("none", "int8", "sign1bit", "topk", "countsketch", "randproj")

# sketch geometry defaults (fed.dcn_sketch_width / fed.dcn_sketch_seed)
DEFAULT_SKETCH_WIDTH = 0.1
DEFAULT_SKETCH_SEED = 0
# randproj chunk: flat tensors project 256 coordinates at a time through a
# shared (256, d) matrix — a full (n, m) matrix would be O(n²·width) memory
_RP_CHUNK = 256


@dataclass(frozen=True)
class CodecCaps:
    """The codec capability contract every dispatch site consults.

    ``decodes_per_contribution`` — each contribution can densify BEFORE
    any reduction (decode-before-reduce): the property that makes robust
    aggregation (trimmed mean / median / clip) legal, because order
    statistics judge CLIENTS and cannot run over summed sketches.
    ``is_linear`` — ``decode(Σ encode(x_c)) == Σ decode(encode(x_c))``:
    the property that makes SUM-THEN-DECODE legal (one decode at the
    root; the async buffer folds in sketch space).
    ``supports_error_feedback`` — the codec's bias is worth banking a
    per-client residual for (``fed.dcn_error_feedback``); unbiased
    codecs (int8 rounding, the sketches) carry none.
    """

    decodes_per_contribution: bool
    is_linear: bool
    supports_error_feedback: bool


CODEC_CAPS: dict[str, CodecCaps] = {
    # "none" is trivially linear: identity commutes with the sum
    "none": CodecCaps(True, True, False),
    "int8": CodecCaps(True, False, False),
    "sign1bit": CodecCaps(True, False, True),
    "topk": CodecCaps(True, False, True),
    "countsketch": CodecCaps(False, True, False),
    "randproj": CodecCaps(False, True, False),
}
assert set(CODEC_CAPS) == set(CODECS)

# codecs whose reconstruction error is biased (sign flips / dropped mass):
# these carry per-client error-feedback residuals when fed.dcn_error_feedback
EF_CODECS = tuple(
    c for c in CODECS if CODEC_CAPS[c].supports_error_feedback
)
# linear sketches: encode into fixed-size images a summing server reduces
LINEAR_SKETCH_CODECS = tuple(
    c for c in CODECS if not CODEC_CAPS[c].decodes_per_contribution
)
# the single payload-dict key each linear sketch rides under — the async
# buffer stores the raw array as an entry leaf and rebuilds the payload
# dict around this key at decode time
SKETCH_PAYLOAD_KEY = {"countsketch": "sketch", "randproj": "proj"}


def validate_codec(name: str) -> str:
    """Fail FAST on a bad codec name. Raised lazily inside a DCN collective,
    a typo would be misread by the watchdog as a peer failure and silently
    degrade every host to standalone training."""
    if name not in CODECS:
        raise ValueError(
            f"unknown fed.dcn_compress codec {name!r}; expected one of "
            f"{CODECS}"
        )
    return name


def codec_caps(codec: str) -> CodecCaps:
    """The capability row for ``codec`` (validates the name)."""
    validate_codec(codec)
    return CODEC_CAPS[codec]


def codec_uses_feedback(codec: str, error_feedback: bool = True) -> bool:
    """True when this codec keeps per-client error-feedback residuals.
    ``auto`` (the adaptive per-layer mode) conservatively allocates them:
    its pinned map may include EF codecs on some leaves."""
    if codec == "auto":
        return error_feedback
    return error_feedback and codec in EF_CODECS


def codec_decodes_per_contribution(codec: str) -> bool:
    """True when each contribution can be decoded to a dense tensor BEFORE
    any reduction — the property that makes robust aggregation (trimmed
    mean / median / clip) legal with this codec (decode-before-reduce).
    The sketches (countsketch / randproj) lack it: their contributions
    only exist pre-aggregated, which is where the robust×compress
    fail-fast lives. Delegates to :data:`CODEC_CAPS`."""
    return codec_caps(codec).decodes_per_contribution


def sketch_dims(size: int, width: float) -> int:
    """Sketch buckets for an ``size``-element tensor at ``width``
    (``fed.dcn_sketch_width``): ``ceil(width * size)``, at least 1, at
    most the tensor size (a sketch wider than the tensor is the tensor)."""
    if not 0.0 < width <= 1.0:
        raise ValueError(
            f"fed.dcn_sketch_width must be in (0, 1], got {width}"
        )
    return max(1, min(int(size), int(np.ceil(width * float(size)))))


def _sketch_hashes(
    seed: int, leaf_id: int, n: int, m: int
) -> tuple[np.ndarray, np.ndarray]:
    """The count-sketch hash ``h : [n] -> [m]`` and sign ``s : [n] -> ±1``
    for one leaf. Derived ONLY from (seed, leaf_id, n, m), so every
    client/process/worker sharing the config derives the SAME maps — the
    precondition for summing sketches across contributions."""
    rng = np.random.default_rng(
        np.random.SeedSequence([int(seed) & 0x7FFFFFFF, int(leaf_id), n, m])
    )
    h = rng.integers(0, m, size=n, dtype=np.int64)
    s = (rng.integers(0, 2, size=n).astype(np.float32) * 2.0 - 1.0)
    return h, s


def _randproj_matrix(seed: int, leaf_id: int, d: int) -> np.ndarray:
    """The shared per-leaf (``_RP_CHUNK``, d) projection with iid
    ``±1/√d`` entries: ``E[R Rᵀ] = I`` makes ``decode = y @ Rᵀ``
    unbiased. Same (seed, leaf_id, d) → same matrix on every client."""
    rng = np.random.default_rng(
        np.random.SeedSequence(
            [(int(seed) + 1) & 0x7FFFFFFF, int(leaf_id), _RP_CHUNK, d]
        )
    )
    signs = rng.integers(0, 2, size=(_RP_CHUNK, d)).astype(np.float32)
    return (signs * 2.0 - 1.0) / np.float32(np.sqrt(d))


def topk_count(size: int, ratio: float) -> int:
    """Coordinates kept per tensor under ``topk``: ``ceil(ratio * size)``,
    at least 1, at most the tensor size."""
    if not 0.0 < ratio <= 1.0:
        raise ValueError(
            f"fed.dcn_topk_ratio must be in (0, 1], got {ratio}"
        )
    return max(1, min(int(size), int(np.ceil(ratio * float(size)))))


# ------------------------------------------------------------ numpy (wire)
def encode_leaf(
    x: np.ndarray,
    codec: str,
    topk_ratio: float = 0.01,
    *,
    sketch_width: float = DEFAULT_SKETCH_WIDTH,
    sketch_seed: int = DEFAULT_SKETCH_SEED,
    leaf_id: int = 0,
) -> dict:
    """One tensor → its wire payload: a flat dict of numpy arrays (a valid
    pytree, so payloads travel through ``process_allgather`` unchanged).
    The payload is everything that crosses the wire; shapes/dtypes are
    host-side metadata both ends already hold (the model config).

    The sketch codecs key their shared hash/projection on
    ``(sketch_seed, leaf_id)`` — both ends must agree on the leaf's index
    in the flattened tree for the payloads to sum."""
    x = np.asarray(x, np.float32)
    if codec == "none":
        return {"dense": x}
    if codec == "countsketch":
        flat = x.reshape(-1)
        n = flat.size
        m = sketch_dims(max(n, 1), sketch_width)
        h, s = _sketch_hashes(sketch_seed, leaf_id, n, m)
        y = np.bincount(h, weights=(s * flat).astype(np.float64), minlength=m)
        return {"sketch": y.astype(np.float32)}
    if codec == "randproj":
        flat = x.reshape(-1)
        n = flat.size
        d = sketch_dims(_RP_CHUNK, sketch_width)
        nchunks = max(1, -(-n // _RP_CHUNK))
        pad = nchunks * _RP_CHUNK - n
        xp = np.pad(flat, (0, pad)).reshape(nchunks, _RP_CHUNK)
        y = xp @ _randproj_matrix(sketch_seed, leaf_id, d)
        return {"proj": y.astype(np.float32)}
    if codec == "int8":
        amax = float(np.max(np.abs(x))) if x.size else 0.0
        scale = np.float32(amax / 127.0)
        if scale == 0.0:
            q = np.zeros(x.shape, np.int8)
        else:
            q = np.clip(np.rint(x / scale), -127, 127).astype(np.int8)
        return {"q": q, "scale": np.float32(scale)}
    if codec == "sign1bit":
        scale = np.float32(np.mean(np.abs(x))) if x.size else np.float32(0.0)
        bits = np.packbits((x >= 0).reshape(-1))
        return {"bits": bits, "scale": scale}
    if codec == "topk":
        flat = x.reshape(-1)
        k = topk_count(flat.size, topk_ratio)
        # descending |x|, ties broken by LOWEST flat index (stable sort on
        # the negated magnitudes) — the same tie-break as lax.top_k, so the
        # in-graph simulation and the wire codec keep identical coordinates
        idx = np.argsort(-np.abs(flat), kind="stable")[:k].astype(np.int32)
        return {"idx": idx, "val": flat[idx].astype(np.float32)}
    raise ValueError(f"unknown codec {codec!r}")  # pragma: no cover


def decode_leaf(
    payload: dict,
    codec: str,
    shape: tuple,
    *,
    sketch_seed: int = DEFAULT_SKETCH_SEED,
    leaf_id: int = 0,
) -> np.ndarray:
    """Wire payload → dense float32 tensor of ``shape``.

    For the linear sketches this is itself a LINEAR map, so it works
    unchanged on a SUMMED payload: ``decode_leaf(Σ sketches)`` IS the
    decode-after-sum step (one decode at the root, no per-contribution
    densify)."""
    if codec == "none":
        return np.asarray(payload["dense"], np.float32).reshape(shape)
    if codec == "countsketch":
        y = np.asarray(payload["sketch"], np.float32)
        n = int(np.prod(shape)) if shape else 1
        h, s = _sketch_hashes(sketch_seed, leaf_id, n, y.shape[0])
        return (s * y[h]).astype(np.float32).reshape(shape)
    if codec == "randproj":
        y = np.asarray(payload["proj"], np.float32)
        n = int(np.prod(shape)) if shape else 1
        r = _randproj_matrix(sketch_seed, leaf_id, y.shape[-1])
        flat = (y @ r.T).reshape(-1)[:n]
        return flat.astype(np.float32).reshape(shape)
    if codec == "int8":
        return payload["q"].astype(np.float32) * np.float32(payload["scale"])
    if codec == "sign1bit":
        n = int(np.prod(shape)) if shape else 1
        scale = np.float32(payload["scale"])
        b = np.unpackbits(np.asarray(payload["bits"], np.uint8))[:n]
        return np.where(b > 0, scale, -scale).astype(np.float32).reshape(shape)
    if codec == "topk":
        n = int(np.prod(shape)) if shape else 1
        out = np.zeros((n,), np.float32)
        out[np.asarray(payload["idx"], np.int64)] = np.asarray(
            payload["val"], np.float32
        )
        return out.reshape(shape)
    raise ValueError(f"unknown codec {codec!r}")  # pragma: no cover


def payload_nbytes(payload: dict) -> int:
    """Measured wire bytes of one leaf's payload — real buffer sizes, not
    dtype arithmetic."""
    return int(sum(np.asarray(v).nbytes for v in payload.values()))


@dataclass
class EncodedTree:
    """One contribution, encoded: the wire pytree plus the host-side
    metadata needed to decode any process's copy of it.

    ``leaf_codecs`` (when set) is the per-leaf codec map pinned by
    ``fed.dcn_compress=auto`` — one codec name per flattened leaf,
    overriding the tree-wide ``codec`` label. ``sketch_width`` /
    ``sketch_seed`` are the shared sketch geometry; every endpoint must
    hold the same pair for payloads to sum."""

    codec: str
    payloads: list          # per-leaf payload dicts — the wire pytree
    shapes: list            # per-leaf dense shapes (host metadata)
    treedef: Any
    leaf_codecs: list | None = None
    sketch_width: float = DEFAULT_SKETCH_WIDTH
    sketch_seed: int = DEFAULT_SKETCH_SEED

    def leaf_codec(self, i: int) -> str:
        return self.codec if self.leaf_codecs is None else self.leaf_codecs[i]

    def nbytes(self) -> int:
        return int(sum(payload_nbytes(p) for p in self.payloads))


def encode_tree(
    tree: Any,
    codec: str,
    topk_ratio: float = 0.01,
    *,
    sketch_width: float = DEFAULT_SKETCH_WIDTH,
    sketch_seed: int = DEFAULT_SKETCH_SEED,
    leaf_codecs: list | None = None,
) -> EncodedTree:
    import jax

    flat, treedef = jax.tree_util.tree_flatten(tree)
    flat = [np.asarray(x, np.float32) for x in flat]
    if leaf_codecs is None:
        validate_codec(codec)
        per_leaf = [codec] * len(flat)
    else:
        if len(leaf_codecs) != len(flat):
            raise ValueError(
                f"per-leaf codec map has {len(leaf_codecs)} entries but the "
                f"tree has {len(flat)} leaves — stale fed.dcn_compress=auto "
                "map for this model config?"
            )
        per_leaf = [validate_codec(c) for c in leaf_codecs]
    return EncodedTree(
        codec=codec,
        payloads=[
            encode_leaf(
                x,
                c,
                topk_ratio,
                sketch_width=sketch_width,
                sketch_seed=sketch_seed,
                leaf_id=i,
            )
            for i, (x, c) in enumerate(zip(flat, per_leaf))
        ],
        shapes=[x.shape for x in flat],
        treedef=treedef,
        leaf_codecs=list(leaf_codecs) if leaf_codecs is not None else None,
        sketch_width=sketch_width,
        sketch_seed=sketch_seed,
    )


def decode_tree(enc: EncodedTree) -> Any:
    import jax

    leaves = [
        decode_leaf(
            p,
            enc.leaf_codec(i),
            s,
            sketch_seed=enc.sketch_seed,
            leaf_id=i,
        )
        for i, (p, s) in enumerate(zip(enc.payloads, enc.shapes))
    ]
    return jax.tree_util.tree_unflatten(enc.treedef, leaves)


def decode_gathered(gathered_payloads: list, enc: EncodedTree) -> Any:
    """Decode an allgathered copy of ``enc``'s wire pytree — every payload
    array carries a leading (P,) process dim — into a tree whose leaves are
    dense ``(P, *shape)`` float32 stacks: exactly what
    ``robust_reduce_tree_np`` (or a weighted mean) consumes. THE
    decode-before-reduce step: each contribution is densified per process
    before any cross-process reduction sees it. Only legal for leaves whose
    codec ``decodes_per_contribution``; the coordinator routes linear
    sketch leaves through :func:`sum_payloads` + ONE :func:`decode_leaf`
    instead."""
    import jax

    leaves = []
    for i, (payload, shape) in enumerate(zip(gathered_payloads, enc.shapes)):
        num_p = int(np.asarray(next(iter(payload.values()))).shape[0])
        rows = [
            decode_leaf(
                {k: np.asarray(v)[p] for k, v in payload.items()},
                enc.leaf_codec(i),
                shape,
                sketch_seed=enc.sketch_seed,
                leaf_id=i,
            )
            for p in range(num_p)
        ]
        leaves.append(np.stack(rows))
    return jax.tree_util.tree_unflatten(enc.treedef, leaves)


def sum_payloads(payload: dict, coeffs: np.ndarray) -> dict:
    """Coefficient-weighted sum of one leaf's allgathered payload over its
    leading (P,) process dim — the SUM-THEN-DECODE reduce for a linear
    sketch leaf. Runs entirely in sketch space: what a dumb summing device
    (or the async buffer) does without ever holding a dense tensor."""
    c = np.asarray(coeffs, np.float32)
    return {
        k: np.tensordot(c, np.asarray(v, np.float32), axes=(0, 0))
        for k, v in payload.items()
    }


def tree_dense_nbytes(tree: Any) -> int:
    """Bytes the same contribution would cost uncompressed (dense f32)."""
    import jax

    return int(
        sum(4 * np.asarray(x).size for x in jax.tree_util.tree_leaves(tree))
    )


def leaf_names(tree: Any) -> list:
    """Stable short names for the flattened leaves of ``tree`` (key paths
    joined with '/'), used as the ``leaf=`` label of the per-layer
    compression telemetry and as the keys of the pinned ``auto`` codec
    map. Deterministic given the tree structure — every process derives
    the same names."""
    import jax

    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    names = []
    for path, _leaf in flat:
        parts = []
        for p in path:
            if hasattr(p, "key"):
                parts.append(str(p.key))
            elif hasattr(p, "name"):
                parts.append(str(p.name))
            elif hasattr(p, "idx"):
                parts.append(str(p.idx))
            else:  # pragma: no cover - exotic pytree nodes
                parts.append(str(p))
        names.append("/".join(parts) if parts else "param")
    return names


def tree_rmse(a: Any, b: Any) -> float:
    """Root-mean-square reconstruction error between two pytrees, pooled
    over every coordinate — the number behind ``fed.dcn_sketch_rmse``."""
    import jax

    sq, n = 0.0, 0
    for xa, xb in zip(jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)):
        d = np.asarray(xa, np.float64) - np.asarray(xb, np.float64)
        sq += float(np.sum(d * d))
        n += int(d.size)
    return float(np.sqrt(sq / max(n, 1)))


# ----------------------------------------------------- jax (in-graph twin)
def jax_encode_decode(
    x,
    codec: str,
    topk_ratio: float = 0.01,
    *,
    sketch_width: float = DEFAULT_SKETCH_WIDTH,
    sketch_seed: int = DEFAULT_SKETCH_SEED,
    leaf_id: int = 0,
):
    """Encode→decode one tensor INSIDE a jitted program: the arithmetic
    twin of ``decode_leaf(encode_leaf(x))``, expressed in jnp so the
    round-end sync can compress per-client updates without leaving the
    compiled round. Same scales, same round-half-to-even, same top-k
    tie-break as the numpy wire codec (pinned in tests/test_comms.py).
    The sketch hashes/projections are trace-time numpy constants keyed on
    (sketch_seed, leaf_id, shape) — identical to the wire codec's, so the
    in-graph simulation and a real sketch round share reconstructions."""
    import jax
    import jax.numpy as jnp

    xf = jnp.asarray(x, jnp.float32)
    if codec == "none":
        return xf
    if codec == "countsketch":
        flat = xf.reshape(-1)
        n = int(flat.shape[0])
        m = sketch_dims(max(n, 1), sketch_width)
        h, s = _sketch_hashes(sketch_seed, leaf_id, n, m)
        hj, sj = jnp.asarray(h), jnp.asarray(s)
        y = jnp.zeros((m,), jnp.float32).at[hj].add(sj * flat)
        return (sj * y[hj]).reshape(xf.shape)
    if codec == "randproj":
        flat = xf.reshape(-1)
        n = int(flat.shape[0])
        d = sketch_dims(_RP_CHUNK, sketch_width)
        r = jnp.asarray(_randproj_matrix(sketch_seed, leaf_id, d))
        nchunks = max(1, -(-n // _RP_CHUNK))
        xp = jnp.pad(flat, (0, nchunks * _RP_CHUNK - n))
        xhat = (xp.reshape(nchunks, _RP_CHUNK) @ r) @ r.T
        return xhat.reshape(-1)[:n].reshape(xf.shape)
    if codec == "int8":
        amax = jnp.max(jnp.abs(xf))
        scale = amax / 127.0
        q = jnp.clip(
            jnp.round(xf / jnp.where(scale > 0, scale, 1.0)), -127, 127
        ).astype(jnp.int8)
        return q.astype(jnp.float32) * scale
    if codec == "sign1bit":
        scale = jnp.mean(jnp.abs(xf))
        return jnp.where(xf >= 0, scale, -scale)
    if codec == "topk":
        flat = xf.reshape(-1)
        k = topk_count(flat.shape[0], topk_ratio)
        # lax.top_k on |x|: descending, ties keep the lowest index — the
        # numpy codec's stable argsort reproduces this exactly
        _, idx = jax.lax.top_k(jnp.abs(flat), k)
        out = jnp.zeros_like(flat).at[idx].set(flat[idx])
        return out.reshape(xf.shape)
    raise ValueError(f"unknown codec {codec!r}")


# -------------------------------------------------- host residual sidecar
@dataclass
class CodecState:
    """Host-side error-feedback state for ONE wire endpoint (a coordinator
    process). The in-graph per-client residuals live in ``ClientState``
    instead (``ef_residual``, a ``fed.population`` sidecar field); this is
    the cross-host DCN gather's single per-process residual."""

    residual: Any = None    # pytree matching the contribution, or None

    def residual_nbytes(self) -> int:
        return 0 if self.residual is None else tree_dense_nbytes(self.residual)


def codec_state_bytes(state: CodecState, round_idx: int) -> bytes:
    """Serialize a process residual for the coordinator's save cadence."""
    import jax

    buf = io.BytesIO()
    leaves = (
        []
        if state.residual is None
        else [np.asarray(x) for x in jax.tree_util.tree_leaves(state.residual)]
    )
    np.savez(
        buf,
        round=np.int64(round_idx),
        count=np.int64(len(leaves)),
        **{f"leaf_{i}": x for i, x in enumerate(leaves)},
    )
    return buf.getvalue()


def load_codec_state(blob: bytes, template_tree: Any) -> tuple[CodecState, int]:
    """Restore a process residual serialized by :func:`codec_state_bytes`.
    ``template_tree`` supplies the pytree structure (the contribution tree);
    a zero-leaf blob restores ``residual=None``."""
    import jax

    with np.load(io.BytesIO(blob)) as z:
        round_idx = int(z["round"])
        count = int(z["count"])
        if count == 0:
            return CodecState(residual=None), round_idx
        leaves = [z[f"leaf_{i}"] for i in range(count)]
    treedef = jax.tree_util.tree_structure(template_tree)
    if treedef.num_leaves != len(leaves):
        raise ValueError(
            f"residual sidecar holds {len(leaves)} leaves but the "
            f"contribution tree has {treedef.num_leaves} — config changed "
            "since the sidecar was written?"
        )
    return CodecState(residual=jax.tree_util.tree_unflatten(treedef, leaves)), round_idx
