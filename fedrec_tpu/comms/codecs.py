"""Update-compression codecs for the client→server sync.

The reference ships the FULL ``state_dict`` — frozen DistilBERT trunk
included — from every client every round over raw TCP (~268 MB/client/round,
Final_Report.pdf §VII.b). PR 1–6 already cut that to the two trainable
towers riding XLA collectives; this module is the next lever (ROADMAP open
item 3): compress the per-round *update* itself. One codec registry serves
both places an update crosses a wire (or a simulated one):

* the **in-graph round-end sync** (``train.step._make_local_sync``): each
  cohort client's round delta is encoded/decoded inside the jitted program
  (the jax variants below), modeling the cross-device uplink — sign1bit and
  topk carry **per-client error-feedback residuals** (a ``ClientState``
  field, spilled/restored through the ``fed.population`` sidecar store) so
  the mass a lossy codec drops re-enters the next round's update
  (EF-signSGD, Karimireddy et al. 2019; the communication-perspective FL
  survey, arXiv:2405.20431);
* the **coordinator's cross-host DCN gather**
  (``parallel.multihost.aggregate_from_hosts``): the numpy variants below
  encode each process's contribution into REAL wire buffers (what
  ``process_allgather`` actually ships), decode every contribution
  per-process before any reduction — so Byzantine-robust aggregators judge
  clients, not quantization noise (decode-before-reduce) — and the byte
  counts published to the metrics registry are measured from those buffers,
  not dtype arithmetic.

The numpy and jax variants implement the SAME arithmetic (same scales, same
round-half-to-even, same top-k tie-break: ties keep the lowest flat index),
pinned against each other in ``tests/test_comms.py``, so a trajectory
simulated in-graph matches what the wire codec would reconstruct.

Codecs:

``none``     — identity; the wire carries dense float32.
``int8``     — symmetric per-tensor int8: ``x ≈ q * scale`` with
               ``scale = max|x| / 127``; worst-case element error
               ``scale/2``. ~4× the wire. No residual (rounding noise is
               zero-mean and bounded).
``sign1bit`` — 1 bit per element + one f32 scale per tensor:
               ``x ≈ sign(x) * mean|x|`` (signSGD with majority-free
               scale). ~32× the wire. Biased — REQUIRES error feedback for
               convergence (``fed.dcn_error_feedback``).
``topk``     — structured sparsification: keep the ``ceil(ratio * n)``
               largest-|x| coordinates per tensor (index + value pairs).
               ``ratio = fed.dcn_topk_ratio``; ~``1/(2*ratio)``× the wire.
               Biased — requires error feedback.

DP ordering contract: per-example clipping and noise happen inside the
train step, *before* any encode ever sees the update — the codec compresses
an already-privatized delta, so the ε-accounting is untouched (pinned in
docs/DESIGN.md §5g).
"""

from __future__ import annotations

import io
from dataclasses import dataclass
from typing import Any

import numpy as np

CODECS = ("none", "int8", "sign1bit", "topk")
# codecs whose reconstruction error is biased (sign flips / dropped mass):
# these carry per-client error-feedback residuals when fed.dcn_error_feedback
EF_CODECS = ("sign1bit", "topk")


def validate_codec(name: str) -> str:
    """Fail FAST on a bad codec name. Raised lazily inside a DCN collective,
    a typo would be misread by the watchdog as a peer failure and silently
    degrade every host to standalone training."""
    if name not in CODECS:
        raise ValueError(
            f"unknown fed.dcn_compress codec {name!r}; expected one of "
            f"{CODECS}"
        )
    return name


def codec_uses_feedback(codec: str, error_feedback: bool = True) -> bool:
    """True when this codec keeps per-client error-feedback residuals."""
    return error_feedback and codec in EF_CODECS


def codec_decodes_per_contribution(codec: str) -> bool:
    """True when each contribution can be decoded to a dense tensor BEFORE
    any reduction — the property that makes robust aggregation (trimmed
    mean / median / clip) legal with this codec (decode-before-reduce).
    Every registered codec has it; an aggregated sketch (e.g. a summed
    count-sketch, or in-network aggregation à la the Smart-NIC offload)
    would not, and is where the robust×compress fail-fast lives."""
    validate_codec(codec)
    return True


def topk_count(size: int, ratio: float) -> int:
    """Coordinates kept per tensor under ``topk``: ``ceil(ratio * size)``,
    at least 1, at most the tensor size."""
    if not 0.0 < ratio <= 1.0:
        raise ValueError(
            f"fed.dcn_topk_ratio must be in (0, 1], got {ratio}"
        )
    return max(1, min(int(size), int(np.ceil(ratio * float(size)))))


# ------------------------------------------------------------ numpy (wire)
def encode_leaf(x: np.ndarray, codec: str, topk_ratio: float = 0.01) -> dict:
    """One tensor → its wire payload: a flat dict of numpy arrays (a valid
    pytree, so payloads travel through ``process_allgather`` unchanged).
    The payload is everything that crosses the wire; shapes/dtypes are
    host-side metadata both ends already hold (the model config)."""
    x = np.asarray(x, np.float32)
    if codec == "none":
        return {"dense": x}
    if codec == "int8":
        amax = float(np.max(np.abs(x))) if x.size else 0.0
        scale = np.float32(amax / 127.0)
        if scale == 0.0:
            q = np.zeros(x.shape, np.int8)
        else:
            q = np.clip(np.rint(x / scale), -127, 127).astype(np.int8)
        return {"q": q, "scale": np.float32(scale)}
    if codec == "sign1bit":
        scale = np.float32(np.mean(np.abs(x))) if x.size else np.float32(0.0)
        bits = np.packbits((x >= 0).reshape(-1))
        return {"bits": bits, "scale": scale}
    if codec == "topk":
        flat = x.reshape(-1)
        k = topk_count(flat.size, topk_ratio)
        # descending |x|, ties broken by LOWEST flat index (stable sort on
        # the negated magnitudes) — the same tie-break as lax.top_k, so the
        # in-graph simulation and the wire codec keep identical coordinates
        idx = np.argsort(-np.abs(flat), kind="stable")[:k].astype(np.int32)
        return {"idx": idx, "val": flat[idx].astype(np.float32)}
    raise ValueError(f"unknown codec {codec!r}")  # pragma: no cover


def decode_leaf(payload: dict, codec: str, shape: tuple) -> np.ndarray:
    """Wire payload → dense float32 tensor of ``shape``."""
    if codec == "none":
        return np.asarray(payload["dense"], np.float32).reshape(shape)
    if codec == "int8":
        return payload["q"].astype(np.float32) * np.float32(payload["scale"])
    if codec == "sign1bit":
        n = int(np.prod(shape)) if shape else 1
        scale = np.float32(payload["scale"])
        b = np.unpackbits(np.asarray(payload["bits"], np.uint8))[:n]
        return np.where(b > 0, scale, -scale).astype(np.float32).reshape(shape)
    if codec == "topk":
        n = int(np.prod(shape)) if shape else 1
        out = np.zeros((n,), np.float32)
        out[np.asarray(payload["idx"], np.int64)] = np.asarray(
            payload["val"], np.float32
        )
        return out.reshape(shape)
    raise ValueError(f"unknown codec {codec!r}")  # pragma: no cover


def payload_nbytes(payload: dict) -> int:
    """Measured wire bytes of one leaf's payload — real buffer sizes, not
    dtype arithmetic."""
    return int(sum(np.asarray(v).nbytes for v in payload.values()))


@dataclass
class EncodedTree:
    """One contribution, encoded: the wire pytree plus the host-side
    metadata needed to decode any process's copy of it."""

    codec: str
    payloads: list          # per-leaf payload dicts — the wire pytree
    shapes: list            # per-leaf dense shapes (host metadata)
    treedef: Any

    def nbytes(self) -> int:
        return int(sum(payload_nbytes(p) for p in self.payloads))


def encode_tree(tree: Any, codec: str, topk_ratio: float = 0.01) -> EncodedTree:
    import jax

    validate_codec(codec)
    flat, treedef = jax.tree_util.tree_flatten(tree)
    flat = [np.asarray(x, np.float32) for x in flat]
    return EncodedTree(
        codec=codec,
        payloads=[encode_leaf(x, codec, topk_ratio) for x in flat],
        shapes=[x.shape for x in flat],
        treedef=treedef,
    )


def decode_tree(enc: EncodedTree) -> Any:
    import jax

    leaves = [
        decode_leaf(p, enc.codec, s) for p, s in zip(enc.payloads, enc.shapes)
    ]
    return jax.tree_util.tree_unflatten(enc.treedef, leaves)


def decode_gathered(gathered_payloads: list, enc: EncodedTree) -> Any:
    """Decode an allgathered copy of ``enc``'s wire pytree — every payload
    array carries a leading (P,) process dim — into a tree whose leaves are
    dense ``(P, *shape)`` float32 stacks: exactly what
    ``robust_reduce_tree_np`` (or a weighted mean) consumes. THE
    decode-before-reduce step: each contribution is densified per process
    before any cross-process reduction sees it."""
    import jax

    leaves = []
    for payload, shape in zip(gathered_payloads, enc.shapes):
        num_p = int(np.asarray(next(iter(payload.values()))).shape[0])
        rows = [
            decode_leaf(
                {k: np.asarray(v)[p] for k, v in payload.items()},
                enc.codec,
                shape,
            )
            for p in range(num_p)
        ]
        leaves.append(np.stack(rows))
    return jax.tree_util.tree_unflatten(enc.treedef, leaves)


def tree_dense_nbytes(tree: Any) -> int:
    """Bytes the same contribution would cost uncompressed (dense f32)."""
    import jax

    return int(
        sum(4 * np.asarray(x).size for x in jax.tree_util.tree_leaves(tree))
    )


# ----------------------------------------------------- jax (in-graph twin)
def jax_encode_decode(x, codec: str, topk_ratio: float = 0.01):
    """Encode→decode one tensor INSIDE a jitted program: the arithmetic
    twin of ``decode_leaf(encode_leaf(x))``, expressed in jnp so the
    round-end sync can compress per-client updates without leaving the
    compiled round. Same scales, same round-half-to-even, same top-k
    tie-break as the numpy wire codec (pinned in tests/test_comms.py)."""
    import jax
    import jax.numpy as jnp

    xf = jnp.asarray(x, jnp.float32)
    if codec == "none":
        return xf
    if codec == "int8":
        amax = jnp.max(jnp.abs(xf))
        scale = amax / 127.0
        q = jnp.clip(
            jnp.round(xf / jnp.where(scale > 0, scale, 1.0)), -127, 127
        ).astype(jnp.int8)
        return q.astype(jnp.float32) * scale
    if codec == "sign1bit":
        scale = jnp.mean(jnp.abs(xf))
        return jnp.where(xf >= 0, scale, -scale)
    if codec == "topk":
        flat = xf.reshape(-1)
        k = topk_count(flat.shape[0], topk_ratio)
        # lax.top_k on |x|: descending, ties keep the lowest index — the
        # numpy codec's stable argsort reproduces this exactly
        _, idx = jax.lax.top_k(jnp.abs(flat), k)
        out = jnp.zeros_like(flat).at[idx].set(flat[idx])
        return out.reshape(xf.shape)
    raise ValueError(f"unknown codec {codec!r}")


# -------------------------------------------------- host residual sidecar
@dataclass
class CodecState:
    """Host-side error-feedback state for ONE wire endpoint (a coordinator
    process). The in-graph per-client residuals live in ``ClientState``
    instead (``ef_residual``, a ``fed.population`` sidecar field); this is
    the cross-host DCN gather's single per-process residual."""

    residual: Any = None    # pytree matching the contribution, or None

    def residual_nbytes(self) -> int:
        return 0 if self.residual is None else tree_dense_nbytes(self.residual)


def codec_state_bytes(state: CodecState, round_idx: int) -> bytes:
    """Serialize a process residual for the coordinator's save cadence."""
    import jax

    buf = io.BytesIO()
    leaves = (
        []
        if state.residual is None
        else [np.asarray(x) for x in jax.tree_util.tree_leaves(state.residual)]
    )
    np.savez(
        buf,
        round=np.int64(round_idx),
        count=np.int64(len(leaves)),
        **{f"leaf_{i}": x for i, x in enumerate(leaves)},
    )
    return buf.getvalue()


def load_codec_state(blob: bytes, template_tree: Any) -> tuple[CodecState, int]:
    """Restore a process residual serialized by :func:`codec_state_bytes`.
    ``template_tree`` supplies the pytree structure (the contribution tree);
    a zero-leaf blob restores ``residual=None``."""
    import jax

    with np.load(io.BytesIO(blob)) as z:
        round_idx = int(z["round"])
        count = int(z["count"])
        if count == 0:
            return CodecState(residual=None), round_idx
        leaves = [z[f"leaf_{i}"] for i in range(count)]
    treedef = jax.tree_util.tree_structure(template_tree)
    if treedef.num_leaves != len(leaves):
        raise ValueError(
            f"residual sidecar holds {len(leaves)} leaves but the "
            f"contribution tree has {treedef.num_leaves} — config changed "
            "since the sidecar was written?"
        )
    return CodecState(residual=jax.tree_util.tree_unflatten(treedef, leaves)), round_idx
