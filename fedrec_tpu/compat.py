"""JAX version compatibility.

``shard_map`` graduated from ``jax.experimental.shard_map`` to the
``jax`` top level, and its replication-check kwarg was renamed
``check_rep`` -> ``check_vma`` along the way.  The framework targets the
new spelling (pyproject pins jax>=0.9) but must still import on older
installs — a serving host is exactly the place where the runtime can lag
the dev pin.  Import :data:`shard_map` from here instead of ``jax``:
call sites keep the modern ``check_vma=...`` kwarg and the shim
translates when the underlying JAX only knows ``check_rep``.
"""

from __future__ import annotations

import inspect

try:  # modern spelling (jax >= 0.6)
    from jax import shard_map as _shard_map
except ImportError:  # pragma: no cover - depends on installed jax
    from jax.experimental.shard_map import shard_map as _shard_map

if "check_vma" in inspect.signature(_shard_map).parameters:
    shard_map = _shard_map
else:

    def shard_map(f, *args, **kwargs):
        if "check_vma" in kwargs:
            kwargs["check_rep"] = kwargs.pop("check_vma")
        return _shard_map(f, *args, **kwargs)


__all__ = ["shard_map"]
