"""fedrec_tpu — a TPU-native federated news-recommendation framework.

A from-scratch JAX/XLA/Pallas re-design of the capabilities of
`VishnoiAman777/FedRec-with-PytorchDistributed` (reference mounted read-only at
/root/reference): privacy-preserving federated learning of a two-tower news
recommender (frozen-DistilBERT text encoder + multihead-attention user
encoder) on the MIND / Adressa datasets.

Design principles (TPU-first, not a port):
  * One jitted SPMD train step over a ``jax.sharding.Mesh`` with a
    ``clients`` axis — each TPU core simulates one federated client; gradient
    / parameter federation is a ``lax.pmean`` over ICI instead of the
    reference's gloo allreduce (reference ``main.py:117``,
    ``Parameter_Averaging_main.py:144-148``).
  * News representations live in an HBM-resident precomputed embedding table
    gathered by nid inside the step, replacing the reference's per-sample
    DistilBERT re-encode hot loop (reference ``model.py:41-61``).
  * Sparse per-nid news-embedding gradients are ``jax.ops.segment_sum``
    scatter-adds with static shapes (reference dict scatter ``main.py:20-52``).
  * Local differential privacy is proper DP-SGD: per-example gradients via
    ``vmap``, clipping, device-side Gaussian noise drawn from per-client PRNG
    keys *before* the collective (honest version of reference
    ``client.py:87-89,271-281``).

Package layout:
  config     — dataclass config system (replaces bare sys.argv parsing)
  data       — MIND/Adressa pipelines, negative sampling, static-shape batchers
  models     — Flax modules: attentions, encoders, two-tower recommender
  ops        — Pallas TPU kernels + XLA fallbacks for the hot ops
  parallel   — mesh construction, sharding, collectives, multi-host rendezvous
  fed        — federated aggregation strategies (grad-avg / param-avg / coordinator)
  privacy    — DP-SGD + RDP accountant (replaces Opacus)
  train      — the single Trainer (ends the reference's 4-way copy-paste)
  eval       — ranking metrics (AUC/MRR/NDCG) host- and device-side
  serve      — batched jitted top-k recommendation over the news table
  utils      — PRNG, logging, profiling helpers
  cli        — entry points mirroring the reference's driver scripts
"""

__version__ = "0.2.0"  # keep in sync with pyproject.toml
