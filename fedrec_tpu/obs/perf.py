"""Performance observability: the ONE home of the repo's efficiency math.

Before this module, every efficiency number lived in an offline bench
script: the peak-FLOPs table and the analytic step-FLOPs model were
private to ``bench.py``, the chip roofline peaks and the
compute/HBM/input-bound verdict private to ``benchmarks/step_profile.py``
— so a production run published no MFU, no bytes-accessed, no roofline
verdict, and an efficiency regression stayed invisible until someone
remembered to run the bench.  This module centralizes:

* **Chip peaks + analytic FLOPs model** — :data:`CHIP_PEAKS` /
  :data:`PEAK_FLOPS` and :func:`flops_per_train_step`, imported back by
  ``bench.py`` and ``benchmarks/step_profile.py`` (one definition serving
  the bench headline, the offline roofline, and the live gauges).
* **Roofline verdict, one spelling** — :func:`roofline_verdict` returns
  the (short key, canonical string) pair; ``step_profile.py`` and the
  live per-round gauges share the exact strings, so the artifacts and
  the telemetry can never desync on the words readers grep for.
* **Compile-cost telemetry** — :class:`CostAnalysisRecorder`, hooked
  into :class:`~fedrec_tpu.obs.device.CompileWatchdog`: every watched
  compilation additionally records the compiled executable's
  ``cost_analysis()`` (FLOPs, bytes accessed, arithmetic intensity)
  into ``xla.cost_*`` gauges — degrading gracefully on backends that
  return ``None`` or partial dicts (gauges skip, never raise).
* **HBM attribution** — :func:`live_array_components` groups
  ``jax.live_arrays()`` bytes by component (params / optimizer state /
  news table / batch buffers / other) into
  ``hbm.component_bytes{component=…}`` gauges at round cadence.
* **The live monitor** — :class:`PerfMonitor`: per-round
  ``perf.mfu`` / ``perf.samples_per_sec`` / roofline-verdict gauges
  computed from the Trainer's existing ``batch_build``/``h2d``/
  ``dispatch`` span timings, plus triggered ``jax.profiler`` capture
  windows (``obs.perf.capture_rounds`` and the efficiency-drop trigger)
  landing inside ``obs.dir`` with a pointer record in ``metrics.jsonl``.

Everything is behind ``obs.perf.enabled`` (default OFF): a disabled run
constructs none of this and executes the byte-identical pre-perf
programs.  ``jax`` is imported lazily inside functions so the obs
package stays importable on artifact-reading boxes with no JAX.

Metric catalogue: ``docs/OBSERVABILITY.md`` §2 (Perf).  Operator
runbook for an MFU drop / input-bound round: ``docs/OPERATIONS.md`` §7e.
"""

from __future__ import annotations

import json
import time
from pathlib import Path
from typing import Any

from fedrec_tpu.obs.fleet import ROUND_PHASES
from fedrec_tpu.obs.registry import MetricsRegistry, get_registry

# ---------------------------------------------------------------- chip peaks
# chip-name fragment -> (bf16 peak FLOP/s, f32 peak FLOP/s, HBM bytes/s).
# THE table: bench.py's MFU headline, step_profile.py's roofline fractions
# and the live perf.mfu gauge all read these same numbers.
CHIP_PEAKS: dict[str, tuple[float, float, float]] = {
    "v5 lite": (197e12, 49e12, 819e9),   # v5e
    "v5e": (197e12, 49e12, 819e9),
    "v4": (275e12, 137e12, 1228e9),
    "v5p": (459e12, 229e12, 2765e9),
    "v6": (918e12, 459e12, 1640e9),      # trillium
}

# bench.py's historical shape: fragment -> (bf16, f32) FLOP/s only
PEAK_FLOPS: dict[str, tuple[float, float]] = {
    k: (v[0], v[1]) for k, v in CHIP_PEAKS.items()
}


def chip_peaks(device_kind: str) -> tuple[float, float, float] | None:
    """(bf16 FLOP/s, f32 FLOP/s, HBM bytes/s) for a device-kind string,
    or ``None`` when the chip is unknown (CPU, new silicon)."""
    kind = (device_kind or "").lower()
    return next((v for frag, v in CHIP_PEAKS.items() if frag in kind), None)


def peak_flops(device_kind: str, dtype: str) -> float | None:
    """The matmul peak the MFU denominator uses, or ``None`` off-chip."""
    peaks = chip_peaks(device_kind)
    if peaks is None:
        return None
    return peaks[0] if dtype == "bfloat16" else peaks[1]


# ------------------------------------------------------------- flops model
def flops_per_train_step(cfg, batch_size: int, num_news: int) -> float:
    """Analytic matmul FLOPs for one joint-mode train step (fwd + bwd),
    PER CLIENT at per-client batch ``batch_size``.

    Counts the dominating dense ops; backward ~= 2x forward for matmuls.
    Moved here from ``bench.py`` (which imports it back) so the bench
    headline, the step_profile roofline and the live ``perf.mfu`` gauge
    can never drift onto different FLOPs models.
    """
    B = batch_size
    C = 1 + cfg.data.npratio
    H = cfg.data.max_his_len
    L = cfg.data.max_title_len
    Dh = cfg.model.bert_hidden
    D = cfg.model.news_dim
    heads, dk = cfg.model.num_heads, cfg.model.head_dim
    Q = cfg.model.query_dim

    # unique-news slots encoded per step — resolved through the SAME policy
    # the compiled step uses (global cap or per-B buckets), so the FLOPs
    # model can never over-count text-tower work the step skipped
    from fedrec_tpu.train.step import resolve_unique_cap

    size = min(B * (C + H), num_news)
    cap = resolve_unique_cap(cfg, B)
    if cap:
        size = min(size, cap)
    att_hidden = Dh // 2               # text-head additive attention hidden
    text = size * (2 * L * Dh * att_hidden + 2 * L * att_hidden + 2 * Dh * D)
    mha = B * (3 * 2 * H * D * D + 2 * 2 * heads * H * H * dk + 2 * H * D)
    pool = B * (2 * H * D * Q + 2 * H * Q)
    score = B * 2 * C * D
    fwd = text + mha + pool + score
    return 3.0 * fwd  # fwd + ~2x fwd for backward


# --------------------------------------------------------- roofline verdict
# ONE spelling of every verdict string: step_profile.py's artifacts and
# the live per-round records must never desync on the words readers and
# docs grep for.  Short keys label the perf.roofline_rounds_total counter
# (Prometheus label values want to stay compact).
VERDICT_INPUT_BOUND = (
    "input-bound: host batch build + transfer >= the device step; "
    "overlap the pipeline (data.prefetch_batches)"
)
VERDICT_MEMORY_BOUND = "memory-bound"
VERDICT_COMPUTE_BOUND = "compute-bound"
VERDICT_HEADROOM = (
    "neither peak approached: dispatch/latency/fusion headroom"
)
VERDICT_DEVICE_BOUND = (
    "device-bound on this backend (host pipeline subdominant; roofline "
    "fractions need a chip run)"
)

ROOFLINE_VERDICTS: dict[str, str] = {
    "input": VERDICT_INPUT_BOUND,
    "memory": VERDICT_MEMORY_BOUND,
    "compute": VERDICT_COMPUTE_BOUND,
    "headroom": VERDICT_HEADROOM,
    "device": VERDICT_DEVICE_BOUND,
}


def roofline_verdict(
    input_bound: bool,
    mfu: float | None = None,
    hbm_fraction: float | None = None,
) -> tuple[str, str]:
    """(short key, canonical string) of the roofline verdict.

    A starved device is input-bound no matter what its roofline fractions
    say.  ``mfu=None`` means no chip peaks are known (CPU backend) — the
    verdict is then device-bound-pending-a-chip-run rather than a
    fraction claim.  Thresholds match ``benchmarks/step_profile.py``'s
    historical artifact semantics (0.6 of either peak).
    """
    if input_bound:
        return "input", VERDICT_INPUT_BOUND
    if mfu is None:
        return "device", VERDICT_DEVICE_BOUND
    if hbm_fraction is not None and hbm_fraction >= 0.6:
        return "memory", VERDICT_MEMORY_BOUND
    if mfu >= 0.6:
        return "compute", VERDICT_COMPUTE_BOUND
    return "headroom", VERDICT_HEADROOM


# ------------------------------------------------------- compile-cost gauges
def analyze_compiled_cost(fn, args: tuple, kwargs: dict | None) -> list[dict] | None:
    """``fn.lower(*args, **kwargs).compile().cost_analysis()`` normalized
    to a list of dicts — or ``None`` when the callable cannot be lowered
    (plain wrapper), the backend returns nothing, or anything raises.
    Never raises: compile-cost telemetry must not perturb training."""
    lower = getattr(fn, "lower", None)
    if lower is None:
        return None
    try:
        cost = lower(*args, **(kwargs or {})).compile().cost_analysis()
    except Exception:  # noqa: BLE001 — any backend failure is "no data"
        return None
    if cost is None:
        return None
    if isinstance(cost, dict):
        return [cost]
    # older jaxlibs return one dict per executable; a watched fn that
    # dispatches several executables returns several
    try:
        entries = [c for c in cost if isinstance(c, dict)]
    except TypeError:
        return None
    return entries or None


class CostAnalysisRecorder:
    """Publishes a watched compilation's ``cost_analysis()`` into gauges.

    Plugged into :class:`~fedrec_tpu.obs.device.CompileWatchdog` via its
    ``cost_cb`` hook: after any watched call during which a NEW
    compilation fired, the watchdog invokes this with the callable and
    its args.  Partial dicts (a backend reporting flops but not bytes)
    publish what exists and skip the rest; multi-executable results sum
    the keys that are present.  A fully absent analysis only counts on
    the ``outcome="unavailable"`` cell — gauges skip, never raise, and
    the watched call's result is never touched."""

    _FLOPS_KEY = "flops"
    _BYTES_KEY = "bytes accessed"

    def __init__(self, registry: MetricsRegistry | None = None):
        self.registry = registry or get_registry()
        self._g_flops = self.registry.gauge(
            "xla.cost_flops",
            "XLA cost_analysis FLOPs of the last-compiled executable, by "
            "watched callable",
            labels=("fn",),
        )
        self._g_bytes = self.registry.gauge(
            "xla.cost_bytes_accessed",
            "XLA cost_analysis bytes accessed (HBM traffic model) of the "
            "last-compiled executable, by watched callable",
            labels=("fn",),
        )
        self._g_intensity = self.registry.gauge(
            "xla.cost_arithmetic_intensity",
            "cost_analysis flops / bytes accessed — compare against the "
            "chip ridge intensity to see which roofline wall is closer",
            labels=("fn",),
        )
        self._c_analyses = self.registry.counter(
            "xla.cost_analyses_total",
            "cost_analysis attempts after watched compilations, by "
            "callable and outcome (ok / unavailable)",
            labels=("fn", "outcome"),
        )

    def __call__(self, fn, args: tuple, kwargs: dict | None, name: str) -> None:
        try:
            entries = analyze_compiled_cost(fn, args, kwargs)
            if not entries:
                self._c_analyses.inc(fn=name, outcome="unavailable")
                return
            # presence, not truthiness: a copy/broadcast program's
            # legitimate 0.0-FLOPs reading is DATA, not a missing key
            flops_vals = [
                float(e[self._FLOPS_KEY]) for e in entries
                if isinstance(e.get(self._FLOPS_KEY), (int, float))
            ]
            byte_vals = [
                float(e[self._BYTES_KEY]) for e in entries
                if isinstance(e.get(self._BYTES_KEY), (int, float))
            ]
            flops = sum(flops_vals) if flops_vals else None
            nbytes = sum(byte_vals) if byte_vals else None
            if flops is None and nbytes is None:
                self._c_analyses.inc(fn=name, outcome="unavailable")
                return
            if flops is not None:
                self._g_flops.set(flops, fn=name)
            if nbytes is not None:
                self._g_bytes.set(nbytes, fn=name)
            if flops is not None and nbytes:  # nbytes > 0: division guard
                self._g_intensity.set(flops / nbytes, fn=name)
            self._c_analyses.inc(fn=name, outcome="ok")
        except Exception:  # noqa: BLE001 — telemetry must never raise
            pass

    def bytes_accessed(self, name: str) -> float | None:
        """Last-recorded bytes-accessed for a watched callable (the live
        HBM-fraction numerator), or None."""
        return self._g_bytes.value(fn=name)


# ----------------------------------------------------------- HBM attribution
def live_array_components(
    components: dict[str, Any],
    registry: MetricsRegistry | None = None,
    tracer: Any = None,
    **annotations: Any,
) -> dict[str, float]:
    """Group every live device array's bytes by component.

    ``components`` maps a component name (``params`` / ``optimizer`` /
    ``news_table`` / ``batch``) to the pytree whose leaves define it;
    classification is by leaf IDENTITY against ``jax.live_arrays()``, so
    a donated/deleted buffer simply stops being live and drops out.
    Everything unclaimed lands in ``other`` (rng keys, eval tables,
    XLA temporaries that surface as arrays).  Bytes are the arrays'
    logical ``nbytes`` — per-device resident bytes divide by the mesh
    axis the leaf is sharded over, which ``device.memory_stats`` (the
    companion gauge) already reports in aggregate.

    Publishes ``hbm.component_bytes{component=…}`` gauges (+ one trace
    instant) and returns the totals.  Never raises; returns ``{}`` when
    ``jax.live_arrays`` is unavailable."""
    registry = registry or get_registry()
    try:
        import jax

        sets: dict[str, set[int]] = {}
        for name, tree in components.items():
            if tree is None:
                continue
            sets[name] = {
                id(leaf)
                for leaf in jax.tree_util.tree_leaves(tree)
                if hasattr(leaf, "dtype")
            }
        totals: dict[str, float] = dict.fromkeys([*sets, "other"], 0.0)
        for arr in jax.live_arrays():
            try:
                nb = float(arr.size) * arr.dtype.itemsize
            except Exception:  # noqa: BLE001 — a dying buffer mid-walk
                continue
            bucket = next(
                (name for name, ids in sets.items() if id(arr) in ids),
                "other",
            )
            totals[bucket] += nb
    except Exception:  # noqa: BLE001 — attribution is best-effort telemetry
        return {}
    gauge = registry.gauge(
        "hbm.component_bytes",
        "live device-array bytes by component (params / optimizer / "
        "news_table / batch / other), sampled at round boundaries",
        labels=("component",),
    )
    for name, nb in totals.items():
        gauge.set(nb, component=name)
    if tracer is not None:
        tracer.instant(
            "hbm_components",
            **{k: int(v) for k, v in totals.items()},
            **annotations,
        )
    return totals


# ------------------------------------------------------------ capture window
def parse_capture_rounds(spec: str) -> tuple[int, int] | None:
    """``"N"`` -> rounds [N, N+1); ``"N:K"`` -> rounds [N, N+K); empty ->
    None.  Raises ValueError on anything else (caught at config time)."""
    spec = (spec or "").strip()
    if not spec:
        return None
    parts = spec.split(":")
    try:
        if len(parts) == 1:
            return int(parts[0]), 1
        if len(parts) == 2:
            start, length = int(parts[0]), int(parts[1])
            if length < 1:
                raise ValueError
            return start, length
    except ValueError:
        pass
    raise ValueError(
        f"cannot parse capture window {spec!r}: expected 'N' (one round) "
        "or 'N:K' (rounds [N, N+K), K >= 1)"
    )


def append_jsonl_record(path, record: dict) -> None:
    """Append one pointer record to a metrics.jsonl event log (the
    discoverability contract for captured traces: the artifact trio
    names every sidecar it produced).  Best-effort — never raises."""
    try:
        with open(path, "a") as f:
            f.write(json.dumps(record) + "\n")
    except OSError:
        pass


class PerfMonitor:
    """Per-round efficiency gauges + triggered capture windows.

    Constructed by the Trainer only when ``obs.perf.enabled``; reads the
    round's ``batch_build``/``h2d``/``dispatch``/``aggregate``/``eval``
    span timings straight off the tracer (the same spans the trace
    artifact carries — no second clock), prices the round with the
    analytic FLOPs model, and publishes:

    * ``perf.samples_per_sec`` / ``perf.mfu`` / ``perf.hbm_fraction``
      (the MFU/HBM gauges only when the chip peaks are known; the HBM
      fraction additionally needs a ``cost_analysis`` bytes-accessed
      reading for the per-batch step program),
    * ``perf.host_ms_per_step`` / ``perf.dispatch_ms_per_step``,
    * ``perf.roofline_rounds_total{verdict=…}`` — the per-round verdict,
      short keys; canonical strings in :data:`ROOFLINE_VERDICTS`.

    Capture windows: ``obs.perf.capture_rounds`` wraps rounds [N, N+K)
    in a ``jax.profiler`` trace under ``obs.dir/perf_capture_rNNNN``;
    ``obs.perf.capture_drop`` arms a one-round capture whenever a
    round's samples/s falls that fraction below the trailing-window
    mean.  Start/stop failures (e.g. a ``train.profile`` trace already
    active) count on ``perf.capture_failures_total`` — never raise."""

    # THE round-phase span names — shared with the fleet straggler
    # attribution so the two digests can never disagree on which spans
    # count as round work
    PHASES = ROUND_PHASES
    MAX_TRIGGERED_CAPTURES = 3

    def __init__(
        self,
        pcfg,
        cfg,
        num_news: int,
        registry: MetricsRegistry | None = None,
        tracer: Any = None,
        obs_dir: Any = None,
        device_kind: str | None = None,
    ):
        from fedrec_tpu.obs.tracing import get_tracer

        self.pcfg = pcfg
        self.cfg = cfg
        self.registry = registry or get_registry()
        self.tracer = tracer or get_tracer()
        self.obs_dir = Path(obs_dir) if obs_dir else None
        if device_kind is None:
            import jax

            device_kind = getattr(jax.devices()[0], "device_kind", "")
        self.peak_fl = peak_flops(device_kind, cfg.model.dtype)
        peaks = chip_peaks(device_kind)
        self.peak_bw = peaks[2] if peaks else None
        self.flops_per_step = flops_per_train_step(
            cfg, cfg.data.batch_size, num_news
        )
        self.samples_per_step = cfg.fed.num_clients * cfg.data.batch_size
        # per-batch dispatch only: a scan/round-chunk dispatch amortizes
        # many steps per executable, so its bytes-accessed reading is not
        # a per-step figure (the gauge stays absent there)
        self._per_batch_dispatch = (
            cfg.train.scan_steps <= 1 and cfg.train.rounds_per_scan <= 1
        )
        self.cost = CostAnalysisRecorder(self.registry)

        self._g_step_flops = self.registry.gauge(
            "perf.step_flops",
            "analytic matmul FLOPs of one train step PER CLIENT "
            "(flops_per_train_step — the same model bench.py certifies "
            "MFU with)",
        )
        self._g_step_flops.set(self.flops_per_step)
        self._g_samples = self.registry.gauge(
            "perf.samples_per_sec",
            "training throughput of the last round (samples = clients x "
            "batch x steps over the round's wall time)",
        )
        self._g_mfu = self.registry.gauge(
            "perf.mfu",
            "model FLOPs utilization of the last round (analytic FLOPs / "
            "wall / chip matmul peak); absent off-chip",
        )
        self._g_hbm_fraction = self.registry.gauge(
            "perf.hbm_fraction",
            "cost_analysis bytes accessed / wall / chip HBM peak of the "
            "last round; needs chip peaks + a per-batch dispatch",
        )
        self._g_host_ms = self.registry.gauge(
            "perf.host_ms_per_step",
            "host input pipeline (batch_build + h2d span time) per "
            "dispatched step, last round",
        )
        self._g_dispatch_ms = self.registry.gauge(
            "perf.dispatch_ms_per_step",
            "device dispatch span time per dispatched step, last round",
        )
        self._c_verdicts = self.registry.counter(
            "perf.roofline_rounds_total",
            "rounds by roofline verdict (input / memory / compute / "
            "headroom / device — canonical strings in obs.perf)",
            labels=("verdict",),
        )
        self._c_untraced = self.registry.counter(
            "perf.untraced_rounds_total",
            "rounds whose phase spans were lost to the tracer capacity "
            "bound (obs.trace_capacity) — no roofline verdict or per-step "
            "phase gauges are published for them, rather than wrong ones",
        )
        self._c_captures = self.registry.counter(
            "perf.captures_total",
            "jax.profiler capture windows started, by reason "
            "(configured / efficiency_drop)",
            labels=("reason",),
        )
        self._c_capture_failures = self.registry.counter(
            "perf.capture_failures_total",
            "capture windows that failed to start/stop (e.g. another "
            "profiler trace already active) — counted, never raised",
        )

        self._steps_counter = self.registry.counter(
            "train.steps_total", "train-step batches dispatched"
        )
        self._mark_events = 0
        self._mark_steps = 0.0
        self._mark_dropped = 0
        self._rates: list[float] = []
        self._window = parse_capture_rounds(pcfg.capture_rounds)
        self._drop = float(pcfg.capture_drop or 0.0)
        if self.obs_dir is None and (self._window is not None or self._drop > 0):
            # fail fast, not silently-never-capture: an explicitly
            # requested window writes its trace + pointer record into the
            # obs artifact directory, so one must exist
            raise ValueError(
                "obs.perf.capture_rounds / obs.perf.capture_drop need "
                "obs.dir set: the jax.profiler trace and its "
                "metrics.jsonl pointer record land in the obs artifact "
                "directory"
            )
        self._drop_window = max(int(pcfg.capture_window), 2)
        self._pending_trigger = False
        self._triggered = 0
        # when the watch layer is live (obs.slo.enabled) the drop trigger
        # routes through the alert engine instead of arming directly:
        # Watch.bind_perf sets the hook and arms via arm_capture() off the
        # alert's firing transition (one lifecycle, no private flag)
        self.watch_hook = None
        self._active: dict | None = None
        self.last_round: dict | None = None

    # ------------------------------------------------------------- rounds
    def begin_round(self) -> None:
        """Mark the tracer/step-counter positions a round's digest diffs
        against; call at round (or chunk) entry."""
        self._mark_events = self.tracer.event_count()
        self._mark_steps = self._steps_counter.value()
        self._mark_dropped = self.tracer.dropped

    def observe_round(
        self, round_idx: int, num_rounds: int, wall_s: float
    ) -> dict[str, Any]:
        """Digest the round (or rounds-in-jit chunk) that just finished:
        publish the gauges and return the per-round log keys
        (``perf.samples_per_sec`` / ``perf.mfu`` / ``perf.verdict``)."""
        steps = self._steps_counter.value() - self._mark_steps
        # a saturated tracer ring (obs.trace_capacity) drops NEW spans —
        # this round's phase sums would then be silently empty, and an
        # input-bound round would masquerade as 'headroom'. Missing data
        # publishes NO verdict, never a wrong one.
        traced = self.tracer.dropped == self._mark_dropped
        phases = {p: 0.0 for p in self.PHASES}
        for ev in self.tracer.events_since(self._mark_events):
            if ev.get("ph") == "X" and ev.get("name") in phases:
                phases[ev["name"]] += float(ev.get("dur", 0.0)) / 1e6
        out: dict[str, Any] = {}
        # the eval span is excluded from the efficiency denominators so an
        # eval-cadence round's MFU/throughput stays comparable to a
        # train-only round's (the eval cost is still visible: it has its
        # own span row in the trace and the report's span table). Only
        # when the spans are trustworthy — a partially-recorded eval span
        # on an untraced round would under-subtract
        wall_s = max(
            float(wall_s) - (phases["eval"] if traced else 0.0), 1e-9
        )
        host_s = phases["batch_build"] + phases["h2d"]
        disp_s = phases["dispatch"]
        if steps > 0 and traced:
            self._g_host_ms.set(host_s / steps * 1e3)
            self._g_dispatch_ms.set(disp_s / steps * 1e3)
        rate = steps * self.samples_per_step / wall_s
        self._g_samples.set(rate)
        out["perf.samples_per_sec"] = round(rate, 2)
        mfu = None
        if self.peak_fl is not None and steps > 0:
            flops = steps * self.cfg.fed.num_clients * self.flops_per_step
            mfu = flops / wall_s / self.peak_fl
            self._g_mfu.set(mfu)
            out["perf.mfu"] = round(mfu, 6)
        hbm_fraction = None
        if self.peak_bw is not None and self._per_batch_dispatch and steps > 0:
            nbytes = self.cost.bytes_accessed("train_step")
            if nbytes:
                hbm_fraction = steps * nbytes / wall_s / self.peak_bw
                self._g_hbm_fraction.set(hbm_fraction)
                out["perf.hbm_fraction"] = round(hbm_fraction, 6)
        if traced:
            # input-bound exactly as step_profile judges it: the host
            # pipeline costs at least as much as the device step it feeds
            input_bound = disp_s > 0 and host_s >= disp_s
            key, _ = roofline_verdict(input_bound, mfu, hbm_fraction)
            self._c_verdicts.inc(num_rounds, verdict=key)
            out["perf.verdict"] = key
        else:
            self._c_untraced.inc(num_rounds)
        self.last_round = {"round": round_idx, **out}
        # efficiency-drop trigger: a round well below the trailing mean
        # arms a capture of the NEXT round (this one is already gone).
        # Untraced rounds stay out of the trigger AND the trailing mean —
        # their eval-uncorrected rate is not comparable, and a spurious
        # trigger would burn one of the bounded captures
        if traced:
            if (
                self._drop > 0
                and self._triggered < self.MAX_TRIGGERED_CAPTURES
            ):
                trailing = self._rates[-self._drop_window:]
                if len(trailing) >= 2:
                    mean = sum(trailing) / len(trailing)
                    if mean > 0 and rate < (1.0 - self._drop) * mean:
                        if self.watch_hook is not None:
                            self.watch_hook(round_idx, rate, mean)
                        else:
                            self._pending_trigger = True
            self._rates.append(rate)
        return out

    # ------------------------------------------------------------ capture
    def arm_capture(self) -> bool:
        """Arm a triggered capture of the next round (the watch layer's
        entry point: called when the efficiency-drop alert fires).
        Returns False once the triggered-capture budget is spent."""
        if self._triggered >= self.MAX_TRIGGERED_CAPTURES:
            return False
        self._pending_trigger = True
        return True

    def capture_before_round(
        self, round_idx: int, num_rounds: int = 1
    ) -> str | None:
        """Start a capture window when the dispatch beginning at round
        ``round_idx`` (covering ``num_rounds`` rounds — a rounds-in-jit
        chunk dispatches several) intersects one: the configured
        [N, N+K) window, or a pending efficiency-drop trigger.  Returns
        the logdir when a window started."""
        if self._active is not None or self.obs_dir is None:
            return None
        reason = None
        end = round_idx + 1
        if self._window is not None:
            start, length = self._window
            # intersection, not membership: under rounds-in-jit a chunk
            # can stride over the window's start round
            if start < round_idx + num_rounds and round_idx < start + length:
                reason, end = "configured", start + length
        if reason is None and self._pending_trigger:
            reason = "efficiency_drop"
            self._pending_trigger = False
            self._triggered += 1
        if reason is None:
            return None
        logdir = self.obs_dir / f"perf_capture_r{round_idx:04d}"
        try:
            import jax

            jax.profiler.start_trace(str(logdir))
        except Exception:  # noqa: BLE001 — e.g. train.profile already tracing
            self._c_capture_failures.inc()
            return None
        self._active = {
            "round": round_idx,
            "end": end,
            "logdir": str(logdir),
            "reason": reason,
        }
        self._c_captures.inc(reason=reason)
        return str(logdir)

    def capture_after_round(self, last_round_idx: int) -> None:
        """Close the active window once its last round completed."""
        if self._active is not None and last_round_idx >= self._active["end"] - 1:
            self._stop_capture(last_round_idx)

    def close(self) -> None:
        """Stop any still-open window (run end / failing exit path) so a
        capture is never left dangling across process exit."""
        if self._active is not None:
            self._stop_capture(self._active["end"] - 1)

    def _stop_capture(self, last_round_idx: int) -> None:
        active, self._active = self._active, None
        try:
            import jax

            jax.profiler.stop_trace()
        except Exception:  # noqa: BLE001
            self._c_capture_failures.inc()
            return
        if self.obs_dir is not None:
            append_jsonl_record(self.obs_dir / "metrics.jsonl", {
                "kind": "perf_capture",
                "round": active["round"],
                "last_round": last_round_idx,
                "reason": active["reason"],
                "logdir": active["logdir"],
                "ts": time.time(),
            })
