"""Unified telemetry: metrics registry, span tracing, export surfaces.

Three pieces, one namespace:

* :mod:`fedrec_tpu.obs.registry` — process-wide metrics registry
  (counters / gauges / fixed-bucket histograms; labeled, thread-safe,
  snapshot-able).  ``MetricLogger``, the serving server/batcher/store,
  the prefetcher, the Trainer and the DP accountant all publish here
  instead of keeping ad-hoc dicts.
* :mod:`fedrec_tpu.obs.tracing` — host-side span tracer emitting
  Chrome-trace/Perfetto JSON; the Trainer pairs its spans with
  ``jax.profiler.StepTraceAnnotation`` so host spans and XLA device
  steps correlate by round number.
* :mod:`fedrec_tpu.obs.report` — JSONL event log + snapshots + trace
  -> one run report; Prometheus text exposition via
  ``MetricsRegistry.to_prometheus`` (served by the serving admin
  protocol's ``{"cmd": "prometheus"}`` and the ``fedrec-obs prom`` CLI).
* :mod:`fedrec_tpu.obs.health` — training-health monitor + flight
  recorder: digests the in-graph numeric sentry's per-client health
  vectors, flags outlier clients, and dumps (batch, state, manifest)
  forensics on non-finite/divergence triggers (``fedrec-obs replay``).
* :mod:`fedrec_tpu.obs.device` — device-layer watchdogs: XLA recompile
  accounting with shape provenance and round-boundary HBM gauges.
* :mod:`fedrec_tpu.obs.quality` — model-quality observability: fixed
  seeded eval slices + per-slice ranking-metric gauges, score/calibration
  digests (ECE) off the jitted eval pass, per-client quality-outlier
  digests, and the serving store's pre-swap drift probe
  (``serve.drift_*``); the banked regression gate is
  ``benchmarks/quality_gate.py``.
* :mod:`fedrec_tpu.obs.perf` — performance observability: the shared
  peak-FLOPs table + analytic step-FLOPs model (one definition serving
  ``bench.py``, ``benchmarks/step_profile.py`` and the live gauges),
  the one-spelling roofline verdict, compile-cost telemetry
  (``cost_analysis`` via the CompileWatchdog hook), ``jax.live_arrays``
  HBM attribution, per-round ``perf.mfu``/throughput gauges and
  triggered profiler capture windows; the banked regression gate is
  ``benchmarks/perf_gate.py``.
* :mod:`fedrec_tpu.obs.fleet` — fleet-wide observability: worker/rank/
  membership-epoch correlation keys on every span and JSONL record, a
  round-cadence telemetry collector with an offline ``worker_*`` merge
  fallback, the merged clock-aligned distributed trace
  (``fedrec-obs fleet-trace``), per-round straggler/critical-path
  attribution (``fedrec-obs fleet``), and counter-baseline continuity
  across supervisor respawns.
* :mod:`fedrec_tpu.obs.wire` — wire-layer observability: the additive
  trace-context envelope every TCP JSON-lines exchange carries (causal
  Perfetto flow arrows across processes), NTP-style per-edge
  clock-offset estimation (the barrier-free alignment source async
  incarnations resolve through), and per-edge ``wire.*`` RTT/byte/error
  telemetry feeding the ``fedrec-obs fleet`` "Wire" panel.
* :mod:`fedrec_tpu.obs.watch` + :mod:`fedrec_tpu.obs.alerts` — the live
  watch layer: declarative SLOs (``obs.slo.objectives``) with
  Google-SRE multi-window burn-rate evaluation at round/heartbeat
  cadence, an EWMA+MAD streaming anomaly detector over the round-cadence
  series, one pending→firing→resolved alert lifecycle (dedup, flap
  suppression) unifying the legacy health/quality/drift/perf triggers,
  fleet-level rules at the collector (persistent straggler, world below
  target, quorum-wait growth, stalled commit version), and the
  ``fedrec-obs alerts``/``tail`` surfaces.

The package imports no JAX at module level — serving and CLI paths pull
it in cheaply (health/device import jax lazily inside functions).
Metric name catalogue and operator how-to: ``docs/OBSERVABILITY.md``.
"""

from fedrec_tpu.obs.registry import (
    DEFAULT_BUCKETS,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    get_registry,
    sanitize_prom_name,
    set_registry,
)
from fedrec_tpu.obs.report import (
    build_report,
    dump_artifacts,
    load_jsonl,
    load_trace,
    render_text,
    rotate_jsonl,
)
from fedrec_tpu.obs.tracing import Tracer, get_tracer, set_tracer
from fedrec_tpu.obs.fleet import (
    FleetPusher,
    TelemetryCollector,
    ensure_fleet_identity,
    get_fleet_identity,
    restore_counter_baseline,
    save_counter_baseline,
    set_fleet_identity,
)
from fedrec_tpu.obs.health import (
    FlightRecorder,
    HealthMonitor,
    TrainingHealthError,
)
from fedrec_tpu.obs.quality import (
    DriftProbe,
    QualityMonitor,
    SlicedEvalAccumulator,
    build_slice_defs,
)
from fedrec_tpu.obs.device import (
    CompileWatchdog,
    sample_device_memory,
    set_active_watchdog,
)
from fedrec_tpu.obs.wire import (
    WIRE_KEY,
    OffsetEstimator,
    configure_wire,
    wire_enabled,
)
from fedrec_tpu.obs.perf import (
    CostAnalysisRecorder,
    PerfMonitor,
    flops_per_train_step,
    live_array_components,
    roofline_verdict,
)
from fedrec_tpu.obs.alerts import Alert, AlertEngine
from fedrec_tpu.obs.watch import (
    AnomalyDetector,
    BurnRateEvaluator,
    FleetRules,
    SloObjective,
    Watch,
    active_alerts,
    alert_records,
    parse_slo_spec,
)

__all__ = [
    "DEFAULT_BUCKETS",
    "Alert",
    "AlertEngine",
    "AnomalyDetector",
    "BurnRateEvaluator",
    "CompileWatchdog",
    "CostAnalysisRecorder",
    "Counter",
    "DriftProbe",
    "FleetPusher",
    "FleetRules",
    "FlightRecorder",
    "Gauge",
    "HealthMonitor",
    "Histogram",
    "MetricsRegistry",
    "OffsetEstimator",
    "PerfMonitor",
    "QualityMonitor",
    "SlicedEvalAccumulator",
    "SloObjective",
    "TelemetryCollector",
    "Tracer",
    "TrainingHealthError",
    "WIRE_KEY",
    "Watch",
    "active_alerts",
    "alert_records",
    "build_report",
    "build_slice_defs",
    "configure_wire",
    "dump_artifacts",
    "ensure_fleet_identity",
    "flops_per_train_step",
    "get_fleet_identity",
    "get_registry",
    "get_tracer",
    "live_array_components",
    "load_jsonl",
    "load_trace",
    "parse_slo_spec",
    "render_text",
    "roofline_verdict",
    "restore_counter_baseline",
    "rotate_jsonl",
    "sample_device_memory",
    "sanitize_prom_name",
    "save_counter_baseline",
    "set_active_watchdog",
    "set_fleet_identity",
    "set_registry",
    "set_tracer",
    "wire_enabled",
]
