"""Model-quality observability: sliced eval, calibration, serving drift.

The paper's headline claims are ACCURACY numbers, yet corpus-wide metric
means hide exactly the failures a federated deployment produces: one
skewed news category, one starved user stratum, one diverging client, one
bad table push.  This module is the host side of the ``obs.quality``
layer (config section :class:`~fedrec_tpu.config.QualityConfig`):

* **slice definitions** — fixed, seeded partitions of the validation set
  (news-category hash buckets, history-length buckets, user-activity
  quantile buckets); :class:`SlicedEvalAccumulator` folds the jitted
  full-pool eval pass's per-impression metric vectors into per-slice
  means without a second eval pass.
* **score/calibration digests** — the eval step's fixed-shape partial
  sums (``fedrec_tpu.eval.metrics.quality_stats_batch``) reduce to score
  histograms, separation stats and reliability-bin ECE here.
* **per-client quality digest** — flags clients whose eval AUC sits
  ``outlier_auc_drop`` below the cohort median.  Informational: it
  composes with the quarantine machinery's ignore set but NEVER triggers
  quarantine itself (a quality dip is a triage signal, not proof of
  poisoning).
* **serving drift probe** — :class:`DriftProbe` scores a pinned, seeded
  probe-user set against the outgoing and incoming store generation
  BEFORE the hot-swap (``EmbeddingStore.publish``), publishing
  score-shift and top-k rank-churn so a bad table push is visible before
  it serves traffic.

Everything here is numpy + registry — no JAX at module level (the obs
package contract); the in-graph half lives in ``eval/metrics.py``.
Metric catalogue: docs/OBSERVABILITY.md §2 (Quality); triage runbook:
docs/OPERATIONS.md §7d.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Mapping, Sequence

import numpy as np

from fedrec_tpu.obs.registry import MetricsRegistry, get_registry

# the four ranking metrics every slice reports — the same quartet
# Trainer.evaluate_full returns corpus-wide
METRIC_KEYS = ("auc", "mrr", "ndcg5", "ndcg10")

# Knuth multiplicative hash constant: a seeded, stable id -> bucket map
# that needs no category metadata (a topic proxy on synthetic corpora;
# real categories can replace it upstream by pre-bucketing ids)
_HASH_MULT = np.uint64(2654435761)
_SEED_MIX = np.uint64(0x9E3779B97F4A7C15)


def parse_hist_edges(spec: str) -> list[int]:
    """``"10,30"`` -> ``[10, 30]`` (strictly increasing ints)."""
    edges = [int(x) for x in spec.split(",") if x.strip() != ""]
    if any(b <= a for a, b in zip(edges, edges[1:])):
        raise ValueError(
            f"obs.quality.hist_len_edges must be strictly increasing, got {spec!r}"
        )
    return edges


def category_buckets_of(ids: np.ndarray, buckets: int, seed: int) -> np.ndarray:
    """Seeded multiplicative-hash bucket per news id — THE fixed category
    slice map.  Deterministic across processes and runs for a given
    (seed, buckets), so banked quality-gate artifacts stay comparable."""
    ids = np.asarray(ids, np.uint64)
    mixed = ids * _HASH_MULT + np.uint64(seed) * _SEED_MIX
    return (mixed % np.uint64(1 << 32) % np.uint64(max(buckets, 1))).astype(np.int64)


@dataclass(frozen=True)
class SliceDef:
    """One named validation-set stratum: ``mask[i]`` selects impression i."""

    name: str                 # e.g. "category=b3", "hist_len=11-30"
    mask: np.ndarray          # (N,) bool over validation impressions


def build_slice_defs(valid_ix: Any, qcfg: Any) -> list[SliceDef]:
    """Fixed, seeded slice definitions over an ``IndexedSamples`` validation
    set — the same partitions every eval (and the banked quality gate)
    reports on:

    * ``category=b<k>``: seeded hash bucket of the POSITIVE news id
      (``category_buckets`` buckets);
    * ``hist_len=<range>``: user history length vs ``hist_len_edges``;
    * ``activity=q<k>``: the impression's user's validation-impression
      count, bucketed into ``activity_buckets`` quantile buckets (users
      missing a ``uidx`` column skip this family).

    Masks within one family partition the set; families overlap (an
    impression is in one category AND one hist-len AND one activity
    slice).  Empty masks are kept — the accumulator counts them as
    skipped slices, which is itself signal (a category bucket with zero
    validation impressions cannot be judged).
    """
    n = len(valid_ix)
    out: list[SliceDef] = []

    cats = category_buckets_of(
        np.asarray(valid_ix.pos), int(qcfg.category_buckets), int(qcfg.seed)
    )
    for b in range(int(qcfg.category_buckets)):
        out.append(SliceDef(f"category=b{b}", cats == b))

    edges = parse_hist_edges(qcfg.hist_len_edges)
    if edges:
        hl = np.asarray(valid_ix.his_len)
        # first bound -1 so zero-history (cold) users land in the first
        # bucket instead of matching no hist_len slice — the family must
        # partition the set, and the coldest users are exactly the
        # stratum the runbook reads this family for
        bounds = [-1, *edges, None]
        for lo, hi in zip(bounds[:-1], bounds[1:]):
            if hi is None:
                out.append(SliceDef(f"hist_len={lo + 1}+", hl > lo))
            else:
                out.append(
                    SliceDef(
                        f"hist_len={max(lo + 1, 0)}-{hi}",
                        (hl > lo) & (hl <= hi),
                    )
                )

    uidx = getattr(valid_ix, "uidx", None)
    q = int(qcfg.activity_buckets)
    if uidx is not None and q > 0 and n > 0:
        uidx = np.asarray(uidx)
        _, inv, counts = np.unique(uidx, return_inverse=True, return_counts=True)
        activity = counts[inv].astype(np.float64)  # per-impression user activity
        # quantile edges over impressions; duplicate edges collapse (a
        # corpus where every user has one impression yields ONE slice)
        qs = np.quantile(activity, np.linspace(0, 1, q + 1)[1:-1])
        edges_a = np.unique(qs)
        bucket = np.searchsorted(edges_a, activity, side="left")
        for b in range(len(edges_a) + 1):
            out.append(SliceDef(f"activity=q{b}", bucket == b))

    return out


class SlicedEvalAccumulator:
    """Folds per-batch per-impression metric vectors into per-slice means.

    The Trainer's full-pool eval loop calls :meth:`add` once per batch
    with the batch's global start index, the jitted step's per-impression
    metric arrays and the keep-weights (0 for wrap-around padding and
    empty-pool impressions); :meth:`finalize` returns
    ``{slice_name: {auc, mrr, ndcg5, ndcg10, count}}`` plus the list of
    skipped (zero-impression) slices.  A second eval pass is never
    needed — slicing is a reweighting of the pass already being paid for.
    """

    def __init__(self, slice_defs: Sequence[SliceDef], n_valid: int):
        self.slice_defs = list(slice_defs)
        self.n_valid = int(n_valid)
        self._sums = {
            s.name: {k: 0.0 for k in METRIC_KEYS} for s in self.slice_defs
        }
        self._counts = {s.name: 0.0 for s in self.slice_defs}

    def add(
        self, start: int, out: Mapping[str, np.ndarray], weights: np.ndarray
    ) -> None:
        w = np.asarray(weights, np.float64)
        idx = np.arange(start, start + w.shape[0])
        valid = idx < self.n_valid
        idx = np.where(valid, idx, 0)
        w = w * valid  # wrap-around pad rows never count (already 0, belt+braces)
        metric = {k: np.asarray(out[k], np.float64).reshape(-1) for k in METRIC_KEYS}
        for s in self.slice_defs:
            sel = s.mask[idx] * w
            c = float(sel.sum())
            if c == 0.0:
                continue
            self._counts[s.name] += c
            for k in METRIC_KEYS:
                self._sums[s.name][k] += float(np.dot(sel, metric[k]))

    def finalize(self) -> tuple[dict[str, dict], list[str]]:
        slices: dict[str, dict] = {}
        skipped: list[str] = []
        for s in self.slice_defs:
            c = self._counts[s.name]
            if c <= 0:
                skipped.append(s.name)
                continue
            slices[s.name] = {
                **{k: self._sums[s.name][k] / c for k in METRIC_KEYS},
                "count": c,
            }
        return slices, skipped


def reduce_quality_sums(acc: Mapping[str, np.ndarray], ece_bins: int) -> dict:
    """Accumulated ``q.*`` partial sums -> the distribution digest:
    score-histogram counts, separation stats, the reliability table and
    ECE.  Pure closed forms — pinned hand-exact in tests/test_quality.py."""
    pos_n = float(acc["q.pos_n"])
    neg_n = float(acc["q.neg_n"])
    out: dict[str, Any] = {
        "pos_hist": np.asarray(acc["q.pos_hist"], np.float64).tolist(),
        "neg_hist": np.asarray(acc["q.neg_hist"], np.float64).tolist(),
        "pos_n": pos_n,
        "neg_n": neg_n,
    }
    if pos_n > 0:
        mean_p = float(acc["q.pos_sum"]) / pos_n
        var_p = max(float(acc["q.pos_sq"]) / pos_n - mean_p**2, 0.0)
        out["pos_mean"], out["pos_std"] = mean_p, var_p**0.5
    if neg_n > 0:
        mean_n = float(acc["q.neg_sum"]) / neg_n
        var_n = max(float(acc["q.neg_sq"]) / neg_n - mean_n**2, 0.0)
        out["neg_mean"], out["neg_std"] = mean_n, var_n**0.5
    if pos_n > 0 and neg_n > 0:
        out["separation"] = out["pos_mean"] - out["neg_mean"]
        pooled = ((out["pos_std"] ** 2 + out["neg_std"] ** 2) / 2.0) ** 0.5
        out["dprime"] = out["separation"] / pooled if pooled > 0 else float("inf")

    cal_n = np.asarray(acc["q.cal_n"], np.float64)
    cal_conf = np.asarray(acc["q.cal_conf"], np.float64)
    cal_label = np.asarray(acc["q.cal_label"], np.float64)
    total = float(cal_n.sum())
    bins = []
    ece = 0.0
    for b in range(ece_bins):
        n_b = float(cal_n[b])
        row = {"bin": b, "count": n_b}
        if n_b > 0:
            row["confidence"] = float(cal_conf[b]) / n_b
            row["accuracy"] = float(cal_label[b]) / n_b
            ece += (n_b / total) * abs(row["accuracy"] - row["confidence"])
        bins.append(row)
    out["calibration"] = bins
    out["ece"] = ece if total > 0 else float("nan")
    return out


class QualityMonitor:
    """Publishes the quality digests into the process registry.

    One instance per Trainer (mirroring :class:`HealthMonitor`); the gate
    benchmark and the ``fedrec-obs quality`` CLI read what it publishes
    (``last_slices`` / ``last_distribution`` / ``last_outliers`` keep the
    raw dicts for in-process consumers)."""

    def __init__(self, qcfg: Any, registry: MetricsRegistry | None = None):
        self.cfg = qcfg
        self.registry = registry or get_registry()
        r = self.registry
        self._g_metric = {
            k: r.gauge(
                f"eval.{k}",
                f"sliced full-pool eval {k} (slice='all' = corpus mean)",
                labels=("slice",),
            )
            for k in METRIC_KEYS
        }
        self._g_slice_n = r.gauge(
            "eval.slice_impressions",
            "validation impressions contributing to the slice's last eval",
            labels=("slice",),
        )
        self._c_skipped = r.counter(
            "eval.slices_skipped_total",
            "slice evaluations skipped because the slice held no scoreable "
            "impression (empty stratum / single-class degenerate)",
        )
        self._g_ece = r.gauge(
            "eval.ece",
            "expected calibration error over the reliability bins of the "
            "last full-pool eval (sigmoid-score confidence vs click rate)",
        )
        self._g_cal_conf = r.gauge(
            "eval.calibration_confidence",
            "mean predicted click probability in the reliability bin",
            labels=("bin",),
        )
        self._g_cal_acc = r.gauge(
            "eval.calibration_accuracy",
            "observed positive rate in the reliability bin",
            labels=("bin",),
        )
        self._g_cal_n = r.gauge(
            "eval.calibration_count",
            "scored candidates in the reliability bin (last eval)",
            labels=("bin",),
        )
        self._h_pos = r.histogram(
            "eval.pos_score", "positive candidate scores (full-pool eval)",
            buckets=self._score_buckets(),
        )
        self._h_neg = r.histogram(
            "eval.neg_score", "negative candidate scores (full-pool eval)",
            buckets=self._score_buckets(),
        )
        self._g_sep = r.gauge(
            "eval.score_separation",
            "mean positive score minus mean negative score (last eval)",
        )
        self._g_dprime = r.gauge(
            "eval.score_dprime",
            "separation / pooled std — the scale-free margin between the "
            "positive and negative score distributions",
        )
        self._g_client_auc = r.gauge(
            "eval.client_auc",
            "per-device-client full-pool eval AUC (diverged clients only; "
            "in-sync cohorts publish the shared value under client 0)",
            labels=("client",),
        )
        self._c_outliers = r.counter(
            "eval.quality_outlier_clients_total",
            "client-evals whose AUC fell obs.quality.outlier_auc_drop below "
            "the cohort median (informational — never triggers quarantine)",
        )
        self._g_outliers = r.gauge(
            "eval.quality_outlier_clients",
            "quality-outlier clients in the last eval",
        )
        self.last_slices: dict[str, dict] = {}
        self.last_skipped: list[str] = []
        self.last_distribution: dict | None = None
        self.last_outliers: list[dict] = []
        # clients whose eval.client_auc cell has ever been written: when
        # the cohort resyncs, every one of them is overwritten with the
        # shared value — a gauge cell from a diverged era must not
        # outlive the divergence (the registry has no cell-delete)
        self._published_clients: set[str] = set()

    def _score_buckets(self) -> tuple:
        lo = -float(self.cfg.score_range)
        width = 2.0 * float(self.cfg.score_range) / int(self.cfg.score_bins)
        return tuple(lo + width * (i + 1) for i in range(int(self.cfg.score_bins) - 1))

    # ---------------------------------------------------------- publishing
    def publish_slices(
        self, slices: Mapping[str, dict], skipped: Sequence[str] = ()
    ) -> None:
        for name, m in slices.items():
            for k in METRIC_KEYS:
                self._g_metric[k].set(float(m[k]), slice=name)
            self._g_slice_n.set(float(m["count"]), slice=name)
        if skipped:
            self._c_skipped.inc(len(skipped))
        self.last_slices = dict(slices)
        self.last_skipped = list(skipped)

    def publish_corpus(self, metrics: Mapping[str, float], count: float) -> None:
        """The corpus-wide quartet under ``slice="all"`` — so one scrape
        shows the mean AND the strata it hides."""
        for k in METRIC_KEYS:
            if k in metrics:
                self._g_metric[k].set(float(metrics[k]), slice="all")
        self._g_slice_n.set(float(count), slice="all")

    def publish_distribution(self, acc: Mapping[str, np.ndarray]) -> dict:
        dist = reduce_quality_sums(acc, int(self.cfg.ece_bins))
        # histogram merge: quality_stats_batch clamps to the edge bins, so
        # bucket i of the in-graph histogram maps 1:1 onto the registry
        # histogram's i-th bucket (last in-graph bin -> +Inf bucket)
        for hist, key, total_key, sum_mean in (
            (self._h_pos, "pos_hist", "pos_n", "pos_mean"),
            (self._h_neg, "neg_hist", "neg_n", "neg_mean"),
        ):
            counts = [int(round(c)) for c in dist[key]]
            n = int(round(dist[total_key]))
            approx_sum = dist.get(sum_mean, 0.0) * n
            hist.merge_counts(counts, approx_sum, n)
        if "separation" in dist:
            self._g_sep.set(dist["separation"])
            self._g_dprime.set(dist["dprime"])
        if np.isfinite(dist["ece"]):
            self._g_ece.set(dist["ece"])
        for row in dist["calibration"]:
            b = str(row["bin"])
            self._g_cal_n.set(row["count"], bin=b)
            if "confidence" in row:
                self._g_cal_conf.set(row["confidence"], bin=b)
                self._g_cal_acc.set(row["accuracy"], bin=b)
        self.last_distribution = dist
        return dist

    # ---------------------------------------------------- per-client digest
    def digest_clients(
        self,
        round_idx: int,
        per_client: Sequence[Mapping[str, float]] | None,
        ignore_clients: set[int] | None = None,
        shared: Mapping[str, float] | None = None,
    ) -> list[dict]:
        """Per-client quality digest at eval cadence.

        ``per_client`` is the Trainer's per-client eval breakdown (None
        when clients are in sync — identical params cannot diverge in
        quality, so ``shared``'s corpus value is published under client 0
        AND over every previously-published client cell: a per-client
        gauge from a diverged era must not survive the resync as if it
        were this eval's number).  Quarantined clients
        (``ignore_clients``) keep their gauge published (their eval is
        real) but are excluded from the median AND from flagging — their
        weight is already 0 and their numbers are the quarantine's
        evidence, not new signal.  Returns the outlier records (also
        kept on ``last_outliers``); NEVER raises or quarantines.
        """
        ignore = ignore_clients or set()
        outliers: list[dict] = []
        if not per_client and shared is not None and "auc" in shared:
            for c in self._published_clients | {"0"}:
                self._g_client_auc.set(float(shared["auc"]), client=c)
            self._published_clients.add("0")
        if per_client:
            all_aucs = {
                c: float(m["auc"])
                for c, m in enumerate(per_client)
                if "auc" in m and np.isfinite(m["auc"])
            }
            for c, a in all_aucs.items():
                self._g_client_auc.set(a, client=str(c))
                self._published_clients.add(str(c))
            aucs = {c: a for c, a in all_aucs.items() if c not in ignore}
            drop = float(self.cfg.outlier_auc_drop or 0.0)
            if drop > 0 and len(aucs) >= 2:
                med = float(np.median(list(aucs.values())))
                for c, a in sorted(aucs.items()):
                    if a < med - drop:
                        outliers.append({
                            "round": int(round_idx),
                            "client": c,
                            "auc": a,
                            "cohort_median": med,
                        })
        if outliers:
            self._c_outliers.inc(len(outliers))
            worst = min(outliers, key=lambda o: o["auc"])
            print(
                f"[quality] quality-outlier client(s) "
                f"{sorted(o['client'] for o in outliers)} in round "
                f"{round_idx}: worst auc {worst['auc']:.4f} vs cohort median "
                f"{worst['cohort_median']:.4f} "
                f"(drop threshold {self.cfg.outlier_auc_drop})"
            )
        self._g_outliers.set(float(len(outliers)))
        self.last_outliers = outliers
        return outliers


# --------------------------------------------------------------------------
# serving drift probe
# --------------------------------------------------------------------------


class DriftProbe:
    """Pinned probe-user set scored against both sides of a store swap.

    ``compare(old_vecs, old_mask, new_vecs, new_mask)`` runs BEFORE the
    new generation becomes current: ``num_probes`` seeded unit-norm probe
    user vectors score every valid catalog row under each table;
    published metrics are the mean/max absolute score shift over rows
    valid in BOTH generations and the mean top-k Jaccard overlap (rank
    churn = 1 - Jaccard).  Identical tables ⇒ shift 0, Jaccard 1, churn 0
    (pinned hand-exact in tests/test_quality.py).  A catalog whose row
    count or embedding dim changed is reported ``comparable=False`` with
    churn metrics only when the id space still matches (same N); scores
    across different dims are meaningless and skipped entirely.
    """

    def __init__(
        self,
        num_probes: int = 32,
        topk: int = 10,
        seed: int = 0,
        registry: MetricsRegistry | None = None,
    ):
        self.num_probes = int(num_probes)
        self.topk = int(topk)
        self.seed = int(seed)
        self.registry = registry or get_registry()
        r = self.registry
        self._g_shift_mean = r.gauge(
            "serve.drift_score_shift_mean",
            "mean |Δscore| over the probe set between the outgoing and "
            "incoming store generation (measured BEFORE the swap)",
        )
        self._g_shift_max = r.gauge(
            "serve.drift_score_shift_max",
            "max |Δscore| over the probe set between generations",
        )
        self._g_jaccard = r.gauge(
            "serve.drift_topk_jaccard",
            "mean probe-user top-k Jaccard overlap between generations "
            "(1.0 = identical rankings)",
        )
        self._g_churn = r.gauge(
            "serve.drift_rank_churn",
            "1 - top-k Jaccard: fraction of each probe's top-k that "
            "changed across the swap",
        )
        self._c_checks = r.counter(
            "serve.drift_checks_total",
            "pre-swap drift probes executed by the embedding store",
        )
        self._probes: dict[int, np.ndarray] = {}
        self.last: dict | None = None

    def _probe_vectors(self, dim: int) -> np.ndarray:
        p = self._probes.get(dim)
        if p is None:
            rng = np.random.default_rng((self.seed, dim))
            p = rng.standard_normal((self.num_probes, dim))
            p /= np.linalg.norm(p, axis=1, keepdims=True)
            self._probes[dim] = p
        return p

    @staticmethod
    def _masked_scores(vecs: np.ndarray, mask, probes: np.ndarray) -> np.ndarray:
        s = probes @ vecs.T  # (P, N)
        if mask is not None:
            s = np.where(np.asarray(mask, bool)[None, :], s, -np.inf)
        return s

    def compare(self, old_vecs, old_mask, new_vecs, new_mask) -> dict:
        old = np.asarray(old_vecs, np.float64)
        new = np.asarray(new_vecs, np.float64)
        result: dict[str, Any] = {
            "probes": self.num_probes, "topk": self.topk, "comparable": True,
        }
        self._c_checks.inc()
        if old.ndim != 2 or new.ndim != 2 or old.shape[1] != new.shape[1]:
            # different embedding dim: neither scores nor ranks compare
            result["comparable"] = False
            self.last = result
            return result
        probes = self._probe_vectors(old.shape[1])
        so = self._masked_scores(old, old_mask, probes)
        sn = self._masked_scores(new, new_mask, probes)

        k = min(self.topk, so.shape[1], sn.shape[1])
        jaccards = []
        for p in range(self.num_probes):
            top_o = set(np.argpartition(-so[p], k - 1)[:k].tolist())
            top_n = set(np.argpartition(-sn[p], k - 1)[:k].tolist())
            jaccards.append(len(top_o & top_n) / max(len(top_o | top_n), 1))
        jac = float(np.mean(jaccards))
        result["topk_jaccard"] = jac
        result["rank_churn"] = 1.0 - jac
        self._g_jaccard.set(jac)
        self._g_churn.set(1.0 - jac)

        if old.shape[0] == new.shape[0]:
            both = np.isfinite(so) & np.isfinite(sn)
            if both.any():
                # subtract on the masked elements only: -inf - -inf on the
                # jointly-invalid rows would warn and yield NaN
                delta = np.abs(so[both] - sn[both])
                result["score_shift_mean"] = float(delta.mean())
                result["score_shift_max"] = float(delta.max())
                self._g_shift_mean.set(result["score_shift_mean"])
                self._g_shift_max.set(result["score_shift_max"])
        else:
            # grown/shrunk catalog: ranks still compare (same id space by
            # convention), per-row score deltas do not
            result["comparable"] = False
        self.last = result
        return result
