"""Wire-layer observability: trace-context envelopes, per-edge telemetry,
and NTP-style clock-offset estimation for the TCP JSON-lines surfaces.

Every cross-process exchange in this system is a JSON object on a TCP
socket — the fleet collector, the membership control plane and the async
commit authority share ONE one-shot exchange pair
(:func:`~fedrec_tpu.obs.fleet.serve_json_line` /
:func:`~fedrec_tpu.obs.fleet.request_json_line`), and the serving path
speaks the same JSON-lines idiom over persistent asyncio connections.
Until this module the wire was the only layer with zero telemetry: the
fleet merger could align barrier deployments (shared ``fed_round``
spans) but an async incarnation — the commit authority above all — fell
back to its raw wall anchor, and "which EDGE gates a commit" had no
answer at all.

Three capabilities, all riding ONE additive envelope key:

* **Trace-context propagation** — a request carries
  ``{"_wire": {trace_id, span_id, send_ts, op, src}}``; the receiver
  opens a child span (``wire.serve``) linked to the sender's
  (``wire.request``) through Perfetto flow events (``ph`` s/t/f with a
  shared ``id``), so the merged fleet trace draws causal arrows from a
  worker's push through the server's fold to the adoption — causality
  by propagation, not clock guessing.

* **NTP-style per-edge clock offsets** — the reply echoes
  ``{recv_ts, reply_ts}``; with the sender's ``send_ts`` and arrival
  ``ack_ts`` the classic estimate is
  ``offset = ((recv - send) + (reply - ack)) / 2`` (receiver clock minus
  sender clock), median'd over a sliding window per edge and published
  as ``wire.clock_offset_ms{peer}``.  ``fleet.estimate_clock_offsets``
  consumes these as a SECOND alignment source: incarnations sharing no
  ``fed_round`` with the reference (async servers, the membership
  service) resolve through the wire-edge graph instead of keeping their
  raw wall anchor.  The bias of the estimate is bounded by half the
  path asymmetry (|forward - return| / 2) — the classic NTP bound,
  pinned in tests/test_wire.py.

* **Per-edge telemetry** — ``wire.{bytes_sent,bytes_recvd,requests,
  errors,reconnects}_total{peer,op}`` counters and ``wire.rtt_ms`` /
  ``wire.server_ms`` histograms on both ends, feeding the ``fedrec-obs
  fleet`` "Wire" panel (per-edge RTT and offset tables, slowest-edge
  callout, queue/wire/fold commit decomposition).

Compatibility contract (pinned in tests/test_wire.py): the envelope is
ADDITIVE.  A receiver that predates it ignores the unknown ``_wire``
key; a receiver that understands it strips the key before op dispatch,
and only echoes a reply envelope when the request carried one — an
old-envelope client gets byte-identical pre-envelope replies.  With
``obs.wire.enabled=false`` no envelope is sent at all and the wire
bytes are byte-identical to the pre-envelope protocol.  Spans follow
the :class:`~fedrec_tpu.obs.tracing.Tracer` ``enabled`` contract: a
process that will never persist a trace records nothing.
"""

from __future__ import annotations

import contextvars
import json
import os
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any

from fedrec_tpu.obs.registry import get_registry
from fedrec_tpu.obs.tracing import get_tracer

__all__ = [
    "WIRE_KEY",
    "OffsetEstimator",
    "configure_wire",
    "wire_enabled",
    "wire_window",
    "new_trace_id",
    "new_span_id",
    "request_envelope",
    "record_client_exchange",
    "record_client_error",
    "record_reconnect",
    "unwrap_envelope",
    "server_reply_envelope",
    "record_server_exchange",
    "current_envelope",
    "serve_extra",
    "last_reply_envelope",
    "peer_offset_s",
    "reset_wire_state",
]

WIRE_KEY = "_wire"

# module switches (obs.wire.* config; configure_wire applies them)
_config_lock = threading.Lock()
_enabled = True
_window = 32


def configure_wire(enabled: bool | None = None, window: int | None = None) -> None:
    """Apply the ``obs.wire.*`` config to this process: ``enabled``
    gates the envelope entirely (off = byte-identical pre-envelope wire
    traffic), ``window`` sizes the per-edge offset median."""
    global _enabled, _window
    with _config_lock:
        if enabled is not None:
            _enabled = bool(enabled)
        if window is not None:
            _window = max(int(window), 1)


def wire_enabled() -> bool:
    return _enabled


def wire_window() -> int:
    return _window


def new_trace_id() -> str:
    return os.urandom(8).hex()


def new_span_id() -> int:
    # 48-bit: comfortably unique per fleet run, JSON-safe as an int id
    return int.from_bytes(os.urandom(6), "big") or 1


# ------------------------------------------------------- offset estimation
class OffsetEstimator:
    """Sliding-window NTP-style offset estimate for one edge.

    ``add(send, recv, reply, ack)`` consumes one exchange's four
    timestamps (sender clock: send/ack; receiver clock: recv/reply) and
    returns the sample's instantaneous offset (receiver minus sender,
    seconds).  ``offset()`` is the window median — robust to the odd
    queue-delayed exchange.  The estimate's bias is bounded by half the
    forward/return path asymmetry (the NTP bound)."""

    def __init__(self, window: int = 32):
        self.samples: deque[float] = deque(maxlen=max(int(window), 1))
        self.rtts: deque[float] = deque(maxlen=max(int(window), 1))

    def add(self, send: float, recv: float, reply: float, ack: float) -> float:
        off = ((recv - send) + (reply - ack)) / 2.0
        self.samples.append(off)
        self.rtts.append(max((ack - send) - (reply - recv), 0.0))
        return off

    def offset(self) -> float | None:
        if not self.samples:
            return None
        s = sorted(self.samples)
        return s[len(s) // 2]


@dataclass
class _WireState:
    """Per-process wire bookkeeping (offset windows + peer-name cache)."""

    lock: threading.Lock = field(default_factory=threading.Lock)
    estimators: dict[str, OffsetEstimator] = field(default_factory=dict)
    # (host, port) -> the peer's self-reported fleet worker id, learned
    # from the first reply envelope so edge labels match the merged
    # fleet's worker ids instead of raw addresses
    peer_names: dict[tuple[str, int], str] = field(default_factory=dict)


_state = _WireState()


def reset_wire_state() -> None:
    """Clear offset windows and the peer-name cache (tests)."""
    global _state
    _state = _WireState()


def peer_offset_s(peer: str) -> float | None:
    """The current windowed offset estimate for ``peer`` (receiver clock
    minus this process's clock, seconds); None before any sample."""
    with _state.lock:
        est = _state.estimators.get(peer)
    return est.offset() if est is not None else None


# ----------------------------------------------------------- client side
def request_envelope(op: str) -> dict:
    """The additive trace-context envelope a client attaches under
    :data:`WIRE_KEY`.  ``src`` is this process's fleet worker id when an
    identity was stamped (lets the receiver label the edge)."""
    from fedrec_tpu.obs.fleet import get_fleet_identity

    env = {
        "trace_id": new_trace_id(),
        "span_id": new_span_id(),
        "send_ts": time.time(),
        "op": str(op),
    }
    src = get_fleet_identity().get("worker")
    if src is not None:
        env["src"] = str(src)
    return env


def _peer_label(host: str, port: int, resp_env: dict | None) -> str:
    key = (str(host), int(port))
    with _state.lock:
        if isinstance(resp_env, dict) and resp_env.get("src"):
            _state.peer_names[key] = str(resp_env["src"])
        return _state.peer_names.get(key, f"{host}:{port}")


def record_client_exchange(
    host: str,
    port: int,
    op: str,
    req_env: dict,
    resp_env: dict | None,
    bytes_sent: int,
    bytes_recvd: int,
    rtt_s: float,
    ack_ts: float,
) -> str:
    """Book one completed client exchange: per-edge counters + RTT
    histogram, the windowed offset update when the reply echoed its
    receive/reply stamps, the ``wire.request`` client span and the flow
    start the receiver's span binds to.  Returns the edge's peer label."""
    peer = _peer_label(host, port, resp_env)
    reg = get_registry()
    _edge_counters(reg, peer, op, bytes_sent, bytes_recvd)
    reg.histogram(
        "wire.rtt_ms",
        "client-observed request round trip per edge",
        labels=("peer", "op"),
    ).observe(rtt_s * 1e3, peer=peer, op=op)
    if isinstance(resp_env, dict) and (
        "recv_ts" in resp_env and "reply_ts" in resp_env
    ):
        recv = float(resp_env["recv_ts"])
        reply = float(resp_env["reply_ts"])
        reg.histogram(
            "wire.server_ms",
            "receiver-side handling time echoed in the reply envelope "
            "(RTT minus this is the pure transport share)",
            labels=("peer", "op"),
        ).observe(max(reply - recv, 0.0) * 1e3, peer=peer, op=op)
        with _state.lock:
            est = _state.estimators.setdefault(
                peer, OffsetEstimator(window=_window)
            )
        est.add(float(req_env["send_ts"]), recv, reply, ack_ts)
        off = est.offset()
        if off is not None:
            reg.gauge(
                "wire.clock_offset_ms",
                "windowed NTP-style clock offset of the peer vs this "
                "process (peer clock minus ours; fleet.estimate_clock_"
                "offsets aligns barrier-less incarnations from it)",
                labels=("peer",),
            ).set(off * 1e3, peer=peer)
    tracer = get_tracer()
    end = tracer.now()
    tracer.add_span(
        "wire.request", rtt_s, end=end,
        op=op, peer=peer, trace_id=req_env.get("trace_id"),
    )
    tracer.flow("out", int(req_env["span_id"]), ts=end - rtt_s / 2.0)
    return peer


def record_client_error(host: str, port: int, op: str) -> None:
    peer = _peer_label(host, port, None)
    get_registry().counter(
        "wire.errors_total",
        "client-side request failures per edge (transport or error reply)",
        labels=("peer", "op"),
    ).inc(peer=peer, op=op)


def record_reconnect(host: str, port: int, op: str = "conn") -> None:
    peer = _peer_label(host, port, None)
    get_registry().counter(
        "wire.reconnects_total",
        "connection re-establishments per edge (persistent-connection "
        "clients; one-shot exchanges never reconnect)",
        labels=("peer", "op"),
    ).inc(peer=peer, op=op)


def _edge_counters(reg, peer: str, op: str, sent: int, recvd: int) -> None:
    reg.counter(
        "wire.requests_total",
        "JSON-lines requests completed per edge",
        labels=("peer", "op"),
    ).inc(peer=peer, op=op)
    if sent:
        reg.counter(
            "wire.bytes_sent_total",
            "request/response line bytes sent per edge",
            labels=("peer", "op"),
        ).inc(float(sent), peer=peer, op=op)
    if recvd:
        reg.counter(
            "wire.bytes_recvd_total",
            "request/response line bytes received per edge",
            labels=("peer", "op"),
        ).inc(float(recvd), peer=peer, op=op)


# ----------------------------------------------------------- server side
@dataclass
class _ServeCtx:
    env: dict
    recv_ts: float
    extra: dict = field(default_factory=dict)


_serve_ctx: contextvars.ContextVar[_ServeCtx | None] = contextvars.ContextVar(
    "fedrec_wire_serve_ctx", default=None
)


def unwrap_envelope(req: dict) -> tuple[dict, dict | None]:
    """Strip the wire envelope off an incoming request BEFORE op
    dispatch — unknown envelope keys must never leak into handlers.
    Returns ``(request_without_envelope, envelope_or_None)``."""
    if isinstance(req, dict) and isinstance(req.get(WIRE_KEY), dict):
        req = dict(req)
        return req, req.pop(WIRE_KEY)
    return req, None


def enter_serve(env: dict, recv_ts: float):
    """Expose the request envelope to the handler for the duration of
    one exchange (:func:`current_envelope` / :func:`serve_extra`);
    returns the token for :func:`exit_serve`."""
    return _serve_ctx.set(_ServeCtx(env=env, recv_ts=recv_ts))


def exit_serve(token) -> None:
    _serve_ctx.reset(token)


def current_envelope() -> dict | None:
    """The wire envelope of the request currently being served on this
    thread/task (None outside a wire-enveloped exchange).  Handlers use
    it to chain flows past the request — e.g. the commit authority links
    a push's flow id to the commit that later folds it."""
    ctx = _serve_ctx.get()
    return ctx.env if ctx is not None else None


def serve_extra(**kv: Any) -> None:
    """Merge extra keys into the CURRENT exchange's reply envelope (e.g.
    ``commit_flow`` so the adopting worker can bind the commit's flow).
    A no-op outside a wire-enveloped exchange."""
    ctx = _serve_ctx.get()
    if ctx is not None:
        ctx.extra.update(kv)


def server_reply_envelope(env: dict, recv_ts: float) -> dict:
    """The reply's envelope echo: the receiver's recv/reply stamps (the
    NTP half the sender needs), its own span id, the sender's trace id,
    this process's identity, plus any :func:`serve_extra` keys."""
    from fedrec_tpu.obs.fleet import get_fleet_identity

    reply: dict[str, Any] = {
        "trace_id": env.get("trace_id"),
        "span_id": new_span_id(),
        "parent": env.get("span_id"),
        "recv_ts": recv_ts,
        "reply_ts": time.time(),
    }
    src = get_fleet_identity().get("worker")
    if src is not None:
        reply["src"] = str(src)
    ctx = _serve_ctx.get()
    if ctx is not None and ctx.extra:
        reply.update(ctx.extra)
    return reply


def record_server_exchange(
    env: dict,
    reply_env: dict,
    op: str,
    bytes_recvd: int,
    bytes_sent: int,
) -> None:
    """Book the receiver's half: per-edge counters labeled by the
    SENDER (the envelope's ``src``), the ``wire.serve`` child span, and
    the flow finish binding the sender's arrow to it."""
    peer = str(env.get("src") or "?")
    reg = get_registry()
    _edge_counters(reg, peer, op, bytes_sent, bytes_recvd)
    dur_s = max(
        float(reply_env.get("reply_ts", 0.0))
        - float(reply_env.get("recv_ts", 0.0)),
        0.0,
    )
    tracer = get_tracer()
    end = tracer.now()
    tracer.add_span(
        "wire.serve", dur_s, end=end,
        op=op, peer=peer,
        trace_id=env.get("trace_id"), parent_span=env.get("span_id"),
    )
    span_id = env.get("span_id")
    if span_id is not None:
        mid = end - dur_s / 2.0 if dur_s > 0 else end
        tracer.flow("in", int(span_id), ts=mid)


# ------------------------------------------------- last-reply plumbing
_thread_local = threading.local()


def _set_last_reply(env: dict | None) -> None:
    _thread_local.last_reply = env


def last_reply_envelope() -> dict | None:
    """The reply envelope of this thread's most recent
    ``request_json_line`` exchange (None when the peer echoed none) —
    how a caller reads :func:`serve_extra` keys the server attached,
    without the response dict itself growing new keys."""
    return getattr(_thread_local, "last_reply", None)


# -------------------------------------------------------- overhead probe
def envelope_overhead_bytes(req: dict) -> int:
    """Measured envelope cost for ``req``: serialized bytes WITH the
    envelope minus without (benchmarks/comm_cost.py asserts this stays
    under 2% of a dense push payload)."""
    bare = len(json.dumps(req).encode())
    full = len(json.dumps({**req, WIRE_KEY: request_envelope(
        str(req.get("cmd", "req"))
    )}).encode())
    return full - bare
