"""Fleet-wide observability: correlation keys, telemetry collection, merged
distributed traces, and straggler attribution.

PRs 3-4 built per-process observability; PRs 10-12 made the system
multi-process and ELASTIC.  A 4-worker elastic run therefore leaves N
disjoint ``obs.dir/worker_*`` artifact trios plus the membership
service's own counters, and "why did round 37 take 3x?" means
hand-correlating them.  Federated systems are diagnosed at the
cohort/round level, not the process level (FedJAX's per-round simulation
metrics); this module supplies the missing fleet layer:

* **Correlation keys** — :func:`set_fleet_identity` stamps
  ``worker``/``rank``/``membership_epoch`` into every span's args
  (tracer context), every registry snapshot (``"fleet"`` key) and every
  MetricLogger JSONL record, so artifacts from different processes are
  joinable offline.

* **Round-cadence telemetry collection** — :class:`TelemetryCollector`
  (standalone via :class:`CollectorServer`, or riding the membership
  service's port — ``python -m fedrec_tpu.parallel.membership ...
  --telemetry-dir D``) accepts ``telemetry_push`` JSON lines from
  :class:`FleetPusher` workers: a registry snapshot plus the spans
  completed since the last push.  It persists them in the SAME
  per-worker layout the offline fallback reads, so a no-collector run
  loses nothing — ``fedrec-obs fleet`` merges the ``worker_*`` obs dirs
  post-hoc either way.

* **Merged distributed trace** — :func:`build_fleet_trace` emits ONE
  Chrome/Perfetto document with a track (pid) per worker.  Clocks are
  aligned in two stages: coarse wall-clock via each tracer's
  ``epoch_unix`` anchor, then a per-incarnation refinement from the
  shared round barrier — every worker's ``fed_round`` N starts at the
  same collective, so the median start skew against a reference worker
  estimates that incarnation's clock offset
  (:func:`estimate_clock_offsets`).  Membership epoch changes, lease
  expiries, joins, quarantines and rollbacks ride along as instants.

* **Straggler / critical-path attribution** —
  :func:`attribute_critical_path` names, per round, the worker whose
  round work gated the barrier (latest aligned ``fed_round`` end), the
  phase that dominated it (batch_build / h2d / dispatch / aggregate /
  eval), and accumulates per-worker times-on-critical-path counters;
  :func:`build_fleet_report` adds per-worker DCN bytes so a slow host, a
  hot catalog shard or a mis-sized cohort reads from one artifact.

* **Counter continuity** — :func:`save_counter_baseline` /
  :func:`restore_counter_baseline` persist a worker's counter totals
  (epoch-tagged) in its obs dir, so a supervisor-respawned worker
  resumes its counters instead of resetting them and ``fedrec-obs
  report`` totals stay monotone across a rejoin.

No JAX imports — usable on any box the artifacts were copied to.
Operator how-to: docs/OBSERVABILITY.md ("Fleet") and docs/OPERATIONS.md
§7c.
"""

from __future__ import annotations

import json
import re
import socket
import threading
import time
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any

from fedrec_tpu.obs.registry import get_registry
from fedrec_tpu.obs.tracing import get_tracer

# the round-work phases attribution breaks a gating round down into
ROUND_PHASES = ("batch_build", "h2d", "dispatch", "aggregate", "eval")

# ------------------------------------------------------------------ identity
_identity_lock = threading.Lock()
_identity: dict[str, Any] = {}


def set_fleet_identity(
    worker: str,
    rank: int | None = None,
    epoch: int | None = None,
    registry=None,
    tracer=None,
) -> dict[str, Any]:
    """Stamp this process's fleet correlation keys everywhere at once:
    the tracer context (merged into every span's args), the registry
    context (the ``"fleet"`` key of every snapshot, which MetricLogger
    also merges into its JSONL records).  ``epoch`` is the membership
    epoch (omit for fixed worlds).  Returns the identity dict."""
    global _identity
    ident: dict[str, Any] = {"worker": str(worker)}
    if rank is not None:
        ident["rank"] = int(rank)
    if epoch is not None:
        ident["membership_epoch"] = int(epoch)
    with _identity_lock:
        _identity = ident
    (tracer or get_tracer()).set_context(**ident)
    (registry or get_registry()).set_context(**ident)
    return dict(ident)


def ensure_fleet_identity(worker: str = "0", rank: int | None = None) -> dict:
    """Set the identity only when no earlier caller (the coordinator CLI,
    which knows the stable worker id and membership epoch) already did —
    the Trainer's constructor hook for fixed-world/single-process runs."""
    with _identity_lock:
        if _identity:
            return dict(_identity)
    return set_fleet_identity(worker, rank=rank)


def get_fleet_identity() -> dict[str, Any]:
    with _identity_lock:
        return dict(_identity)


def reset_fleet_identity() -> None:
    """Clear the process identity (tests)."""
    global _identity
    with _identity_lock:
        _identity = {}


# ---------------------------------------------------------------- collector
_WORKER_ID_BAD = re.compile(r"[^A-Za-z0-9_.-]")


def _safe_worker_id(worker: str) -> str:
    return _WORKER_ID_BAD.sub("_", str(worker)) or "unknown"


class TelemetryCollector:
    """The fleet's round-cadence telemetry sink.

    ``handle(request)`` consumes one ``telemetry_push`` dict (a registry
    snapshot + the spans completed since the worker's last push) and
    appends it to ``<dir>/worker_<id>/metrics.jsonl`` — snapshots as
    ordinary ``registry_snapshot`` lines, spans as ``trace_events``
    lines keyed by the pushing incarnation's ``epoch_unix`` clock
    anchor.  That is deliberately the SAME layout the offline
    ``worker_*`` fallback reads (:func:`load_fleet_dir`), so a collector
    dir and a post-hoc merge of the workers' own obs dirs render through
    identical code paths.

    Transport-agnostic: :class:`CollectorServer` wraps it standalone;
    ``MembershipServer(collector=...)`` routes the same commands over the
    membership port (one control-plane address per federation).

    Each worker's log is size-rotated (``jsonl_max_mb``, one ``.1`` level
    — the same bound the Trainer's ``obs.jsonl_max_mb`` applies), so a
    long-lived federation pushing every round cannot grow the collector
    dir without bound.
    """

    def __init__(self, directory, jsonl_max_mb: float = 256.0):
        self.directory = Path(directory)
        self.directory.mkdir(parents=True, exist_ok=True)
        self.jsonl_max_mb = float(jsonl_max_mb)
        self._lock = threading.Lock()
        self.pushes = 0
        self.workers: dict[str, dict] = {}
        # fleet-level watch rules (fedrec_tpu.obs.watch.FleetRules),
        # evaluated per push when attached; their alert records land in
        # worker_fleet/metrics.jsonl through the rules' own engine
        self.rules = None

    def handle(self, req: dict) -> dict:
        cmd = req.get("cmd")
        if cmd == "telemetry_push":
            return self._push(req)
        if cmd == "telemetry_status":
            return self.status()
        return {"error": f"unknown telemetry cmd {cmd!r}"}

    def _push(self, req: dict) -> dict:
        worker = req.get("worker")
        if worker is None:
            return {"error": "telemetry_push requires a worker id"}
        wid = _safe_worker_id(worker)
        fleet = {
            k: req[k]
            for k in ("worker", "rank", "membership_epoch")
            if req.get(k) is not None
        }
        lines: list[str] = []
        snap = req.get("snapshot")
        if isinstance(snap, dict):
            if fleet and "fleet" not in snap:
                snap = {**snap, "fleet": fleet}
            lines.append(json.dumps(snap))
        events = req.get("events")
        if events:
            lines.append(json.dumps({
                "kind": "trace_events",
                "epoch_unix": float(req.get("epoch_unix") or 0.0),
                "fleet": fleet,
                "events": events,
            }))
        # alert transition records ride the same envelope; written into
        # the worker's log verbatim so fedrec-obs alerts/tail/fleet read
        # them from a collector dir exactly as from an offline obs dir
        for rec in req.get("alerts") or ():
            if isinstance(rec, dict):
                lines.append(json.dumps(rec))
        with self._lock:
            wdir = self.directory / f"worker_{wid}"
            wdir.mkdir(parents=True, exist_ok=True)
            if lines:
                from fedrec_tpu.obs.report import rotate_jsonl

                rotate_jsonl(wdir / "metrics.jsonl", self.jsonl_max_mb)
                with open(wdir / "metrics.jsonl", "a") as f:
                    f.write("\n".join(lines) + "\n")
            self.pushes += 1
            w = self.workers.setdefault(
                wid, {"pushes": 0, "events": 0, "first_push": time.time()}
            )
            w["pushes"] += 1
            w["events"] += len(events or ())
            w["last_push"] = time.time()
            for k, v in fleet.items():
                w[k] = v
        if self.rules is not None:
            try:
                self.rules.observe_push(wid, snap)
            except Exception:  # noqa: BLE001 — a rule bug must not
                pass           # break telemetry ingestion
        return {"ok": True, "worker": wid}

    def status(self) -> dict:
        with self._lock:
            return {
                "dir": str(self.directory),
                "pushes": self.pushes,
                "workers": {k: dict(v) for k, v in self.workers.items()},
            }


def request_json_line(
    host: str, port: int, req: dict, timeout_s: float, op: str | None = None,
    connect_timeout_s: float | None = None,
) -> dict:
    """THE client half of the one-shot JSON-lines exchange: connect,
    send one request line, read one response line.  Raises ``OSError``
    on transport failure (a hang-up with no response line included — an
    ack-less close is NOT a response) and ``ValueError`` on a malformed
    or ``{"error": ...}`` reply.  Shared by :class:`FleetPusher`,
    ``MembershipClient`` and the async agg worker so the client wire
    protocol cannot drift.

    ``connect_timeout_s`` splits the dial deadline from the exchange
    deadline (``timeout_s``): a dead host should fail in connect time,
    while a live peer mid-fold gets the full read budget.  ``None``
    keeps the historical single-deadline behavior.

    Wire observability (:mod:`fedrec_tpu.obs.wire`, default on): the
    request carries an additive trace-context envelope, the reply's
    envelope (if the peer echoes one) is stripped off before return and
    feeds the per-edge RTT/offset telemetry — callers see the exact
    pre-envelope response surface either way.  ``op`` labels the edge
    (defaults to the request's ``cmd``)."""
    from fedrec_tpu.obs import wire

    req_env = None
    if wire.wire_enabled():
        op = op or str(req.get("cmd", "req"))
        req_env = wire.request_envelope(op)
        req = {**req, wire.WIRE_KEY: req_env}
    line = (json.dumps(req) + "\n").encode()
    t0 = time.perf_counter()
    dial_s = timeout_s if connect_timeout_s is None else connect_timeout_s
    try:
        with socket.create_connection((host, port), timeout=dial_s) as conn:
            conn.settimeout(timeout_s)
            conn.sendall(line)
            buf = b""
            while b"\n" not in buf:
                chunk = conn.recv(65536)
                if not chunk:
                    break
                buf += chunk
        if not buf:
            raise OSError("empty response (connection closed before a reply)")
        resp = json.loads(buf.split(b"\n", 1)[0].decode())
        if isinstance(resp, dict) and resp.get("error"):
            raise ValueError(str(resp["error"]))
    except (OSError, ValueError):
        if req_env is not None:
            wire.record_client_error(host, port, str(op))
        raise
    ack_ts = time.time()
    resp, resp_env = wire.unwrap_envelope(resp)
    if req_env is not None:
        wire._set_last_reply(resp_env)
        wire.record_client_exchange(
            host, port, str(op), req_env, resp_env,
            bytes_sent=len(line), bytes_recvd=len(buf),
            rtt_s=time.perf_counter() - t0, ack_ts=ack_ts,
        )
    return resp


def serve_json_line(
    conn: socket.socket,
    handler,
    timeout_s: float = 30.0,
    recv_bytes: int = 1 << 20,
) -> None:
    """THE one-request JSON-lines exchange: read one request line, answer
    ``handler(request)`` as one response line.  A torn or malformed
    connection answers ``{"error": "bad request"}`` where possible and
    never raises — shared by :class:`CollectorServer`, the membership
    service and the async commit authority so the wire protocol cannot
    drift between servers.

    Wire observability (:mod:`fedrec_tpu.obs.wire`): an incoming
    trace-context envelope is stripped BEFORE ``handler`` sees the
    request (unknown envelope keys never leak into op dispatch) and a
    reply envelope is echoed ONLY when the request carried one — a
    client that predates the envelope gets byte-identical pre-envelope
    replies."""
    from fedrec_tpu.obs import wire

    with conn:
        try:
            conn.settimeout(timeout_s)
            buf = b""
            while b"\n" not in buf:
                chunk = conn.recv(recv_bytes)
                if not chunk:
                    return  # hung up before a full request line: no reply
                buf += chunk
            req_line = buf.split(b"\n", 1)[0]
            recv_ts = time.time()
            req = json.loads(req_line.decode())
            env = None
            if isinstance(req, dict):
                req, env = wire.unwrap_envelope(req)
            if env is None:
                resp = handler(req)
                conn.sendall((json.dumps(resp) + "\n").encode())
                return
            token = wire.enter_serve(env, recv_ts)
            try:
                resp = handler(req)
                reply_env = wire.server_reply_envelope(env, recv_ts)
            finally:
                wire.exit_serve(token)
            if isinstance(resp, dict):
                resp = {**resp, wire.WIRE_KEY: reply_env}
            out = (json.dumps(resp) + "\n").encode()
            conn.sendall(out)
            wire.record_server_exchange(
                env, reply_env, op=str(env.get("op") or "req"),
                bytes_recvd=len(req_line) + 1, bytes_sent=len(out),
            )
        except (OSError, ValueError, KeyError):
            try:
                conn.sendall(b'{"error": "bad request"}\n')
            except OSError:
                pass


class CollectorServer:
    """Standalone TCP JSON-lines front for a :class:`TelemetryCollector`
    (the same wire idiom as the membership service and serving admin
    channel: one request line in, one response line out), serving each
    connection through :func:`serve_json_line`."""

    def __init__(self, collector: TelemetryCollector,
                 host: str = "127.0.0.1", port: int = 0):
        self.collector = collector
        self.host = host
        self.port = port
        self._srv: socket.socket | None = None
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def start(self) -> "CollectorServer":
        srv = socket.create_server((self.host, self.port))
        srv.settimeout(0.5)
        self._srv = srv
        self.port = srv.getsockname()[1]
        self._thread = threading.Thread(target=self._accept_loop, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._srv is not None:
            try:
                self._srv.close()
            except OSError:
                pass
        if self._thread is not None:
            self._thread.join(timeout=2.0)

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    def _accept_loop(self) -> None:
        assert self._srv is not None
        while not self._stop.is_set():
            try:
                conn, _ = self._srv.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            threading.Thread(
                target=serve_json_line, args=(conn, self.collector.handle),
                daemon=True,
            ).start()


class FleetPusher:
    """One worker's push side of the collector protocol.

    ``push()`` ships the current registry snapshot plus the trace events
    recorded since the previous push (disjoint slices — the collector
    never sees a span twice) over a fresh TCP connection.  Failures are
    COUNTED (``obs.fleet_push_failures_total``), never raised: telemetry
    must not take down training, and the offline ``worker_*`` artifacts
    remain the lossless fallback.  After ``_BACKOFF_AFTER`` consecutive
    failures, round-cadence pushes are SKIPPED for an exponentially
    growing window (a packet-dropping collector would otherwise stall
    every round by the full connect timeout); ``final=True`` pushes
    always try — they are once-per-run and bounded — and get one
    bounded retry, since a single transient failure there would lose
    the last round's telemetry outright.  Identity
    (worker/rank/epoch) is read from :func:`get_fleet_identity` at push
    time unless given."""

    _BACKOFF_AFTER = 3          # consecutive failures before skipping
    _BACKOFF_BASE_S = 30.0
    _BACKOFF_MAX_S = 600.0
    _FINAL_RETRY_DELAY_S = 1.0  # the final push's single bounded retry

    def __init__(
        self,
        address: str,
        worker: str | None = None,
        registry=None,
        tracer=None,
        timeout_s: float = 5.0,
        push_every: int = 1,
    ):
        host, port = str(address).rsplit(":", 1)
        self.host, self.port = host, int(port)
        self.worker = worker
        self.timeout_s = float(timeout_s)
        self.push_every = max(int(push_every), 1)
        self.registry = registry or get_registry()
        self.tracer = tracer or get_tracer()
        self._sent_events = 0
        # alert engine whose transition records ride the push envelope
        # (set by the Trainer when the watch layer is live); the same
        # disjoint-slice contract as trace events
        self.engine = None
        self._sent_alerts = 0
        self.failures = 0
        self._consec_failures = 0
        self._backoff_until = 0.0
        self._m_pushes = self.registry.counter(
            "obs.fleet_pushes_total",
            "telemetry pushes delivered to the fleet collector",
        )
        self._m_failures = self.registry.counter(
            "obs.fleet_push_failures_total",
            "telemetry pushes that failed (unreachable/torn collector); "
            "the offline worker_* artifacts remain the lossless fallback",
        )

    def maybe_push(self, round_idx: int) -> bool | None:
        """Round-cadence hook: push when ``round_idx`` completes a
        ``push_every`` stride; None when off-cadence."""
        if (round_idx + 1) % self.push_every != 0:
            return None
        return self.push()

    def push(self, final: bool = False) -> bool:
        if not final and time.monotonic() < self._backoff_until:
            return False  # backing off a dead collector: skip, don't stall
        ident = get_fleet_identity()
        worker = self.worker if self.worker is not None else ident.get("worker", "0")
        events = self.tracer.events()
        new = events[self._sent_events:]
        alerts: list = []
        next_alert_idx = self._sent_alerts
        if self.engine is not None:
            alerts, next_alert_idx = self.engine.records_since(
                self._sent_alerts
            )
        req = {
            "cmd": "telemetry_push",
            "worker": str(worker),
            "rank": ident.get("rank"),
            "membership_epoch": ident.get("membership_epoch"),
            "epoch_unix": self.tracer.epoch_unix,
            "snapshot": self.registry.snapshot(),
            "events": new,
            "alerts": alerts,
            "final": bool(final),
        }
        # a FINAL push is once-per-run — its failure loses the last
        # round's telemetry outright, so it gets one bounded retry where
        # round-cadence pushes (a later round will re-carry the snapshot)
        # stay single-attempt
        attempts = 2 if final else 1
        delivered = False
        for attempt in range(attempts):
            try:
                request_json_line(self.host, self.port, req, self.timeout_s)
                delivered = True
                break
            except (OSError, ValueError):
                self.failures += 1
                self._consec_failures += 1
                self._m_failures.inc()
                if attempt + 1 < attempts:
                    time.sleep(self._FINAL_RETRY_DELAY_S)
        if not delivered:
            if self._consec_failures >= self._BACKOFF_AFTER:
                delay = min(
                    self._BACKOFF_BASE_S
                    * 2 ** (self._consec_failures - self._BACKOFF_AFTER),
                    self._BACKOFF_MAX_S,
                )
                self._backoff_until = time.monotonic() + delay
            return False
        # only advance past events the collector acknowledged
        self._sent_events += len(new)
        self._sent_alerts = next_alert_idx
        self._consec_failures = 0
        self._backoff_until = 0.0
        self._m_pushes.inc()
        return True


# ---------------------------------------------------------- counter baselines
COUNTER_BASELINE_FILE = "counters.json"


def counter_baseline(registry=None) -> dict:
    """Every counter's current cells as a JSON-serializable baseline —
    what a respawned incarnation of this worker re-seeds its registry
    with so totals resume instead of resetting."""
    registry = registry or get_registry()
    snap = registry.snapshot()
    counters: dict[str, Any] = {}
    for name, m in snap.get("metrics", {}).items():
        if m.get("kind") != "counter":
            continue
        cells = [
            {"labels": row.get("labels", {}), "value": row["value"]}
            for row in m.get("values", [])
            if row.get("value")
        ]
        if cells:
            counters[name] = {
                "help": m.get("help", ""),
                # label NAMES in declaration order (a snapshot row's label
                # dict preserves it, and so does JSON) — restore must
                # re-register with the exact order or the registry's
                # label-tuple identity check rejects the production
                # registration that follows
                "labels": list(cells[0]["labels"]),
                "cells": cells,
            }
    return counters


def save_counter_baseline(obs_dir, registry=None, epoch: int | None = None) -> Path:
    """Persist the worker's counter totals (epoch-tagged) in its obs dir
    (``counters.json``); :func:`restore_counter_baseline` re-seeds a
    respawned incarnation from it."""
    out = Path(obs_dir)
    out.mkdir(parents=True, exist_ok=True)
    path = out / COUNTER_BASELINE_FILE
    doc = {
        "kind": "counter_baseline",
        "ts": time.time(),
        "epoch": epoch,
        "counters": counter_baseline(registry),
    }
    tmp = path.with_name(path.name + ".tmp")
    tmp.write_text(json.dumps(doc))
    tmp.replace(path)
    return path


def restore_counter_baseline(obs_dir, registry=None) -> int | None:
    """Re-seed the registry's counters from a previously saved baseline;
    returns the baseline's membership epoch tag (None when absent or no
    baseline exists).  Kind conflicts and torn files are skipped, not
    fatal — a lost baseline only costs continuity, never the run."""
    path = Path(obs_dir) / COUNTER_BASELINE_FILE
    if not path.exists():
        return None
    registry = registry or get_registry()
    try:
        doc = json.loads(path.read_text())
    except (OSError, ValueError):
        return None
    if doc.get("kind") != "counter_baseline":
        return None
    for name, m in doc.get("counters", {}).items():
        for cell in m.get("cells", ()):
            labels = cell.get("labels", {})
            # declaration-order label names: explicit when the baseline
            # recorded them, else the cell dict's own (JSON-preserved)
            # key order — NEVER sorted, which would collide with the
            # registry's order-sensitive re-registration check
            names = tuple(m.get("labels") or labels)
            try:
                registry.counter(
                    name, m.get("help", ""), labels=names
                ).inc(float(cell["value"]), **labels)
            except (ValueError, KeyError, TypeError):
                continue  # kind/label conflict or torn cell: skip it
    epoch = doc.get("epoch")
    return int(epoch) if epoch is not None else None


# ----------------------------------------------------------------- loading
@dataclass
class WorkerTrace:
    """One incarnation's worth of trace events with its wall-clock anchor."""

    epoch_unix: float
    events: list[dict] = field(default_factory=list)
    tag: str = ""


@dataclass
class WorkerData:
    """Everything the fleet layer knows about one worker."""

    worker: str
    snapshots: list[dict] = field(default_factory=list)
    records: list[dict] = field(default_factory=list)
    traces: list[WorkerTrace] = field(default_factory=list)
    path: str = ""

    def last_snapshot(self) -> dict | None:
        return self.snapshots[-1] if self.snapshots else None


def load_worker_dir(path, worker: str | None = None) -> WorkerData:
    """One worker's artifacts — an obs trio dir (trace.json +
    epoch-tagged trace_e*.json siblings) and/or a collector-written dir
    (``trace_events`` lines inside metrics.jsonl)."""
    from fedrec_tpu.obs.report import load_jsonl, load_trace

    p = Path(path)
    wid = worker if worker is not None else p.name.removeprefix("worker_")
    data = WorkerData(worker=str(wid), path=str(p))
    metrics = p / "metrics.jsonl"
    if metrics.exists() or Path(str(metrics) + ".1").exists():
        try:
            records, snapshots = load_jsonl(metrics)
        except (OSError, FileNotFoundError):
            records, snapshots = [], []
        data.snapshots = snapshots
        pushed: dict[float, WorkerTrace] = {}
        for r in records:
            if r.get("kind") == "trace_events":
                anchor = float(r.get("epoch_unix") or 0.0)
                tr = pushed.setdefault(
                    anchor, WorkerTrace(epoch_unix=anchor, tag="pushed")
                )
                tr.events.extend(
                    e for e in r.get("events", ()) if isinstance(e, dict)
                )
            else:
                data.records.append(r)
        data.traces.extend(pushed[k] for k in sorted(pushed))
    # epoch-tagged incarnation traces win over the latest-incarnation
    # trace.json (which duplicates the newest tagged file when both exist)
    tagged = sorted(p.glob("trace_*.json"))
    for f in tagged:
        tr = _load_trace_file(f, load_trace)
        if tr is not None:
            tr.tag = f.stem.removeprefix("trace_")
            data.traces.append(tr)
    if not tagged and (p / "trace.json").exists():
        tr = _load_trace_file(p / "trace.json", load_trace)
        if tr is not None:
            data.traces.append(tr)
    return data


def _load_trace_file(path, load_trace) -> WorkerTrace | None:
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return None
    if isinstance(doc, dict):
        events = [e for e in doc.get("traceEvents", ()) if isinstance(e, dict)]
        anchor = float(doc.get("otherData", {}).get("epoch_unix") or 0.0)
    else:
        events = [e for e in doc if isinstance(e, dict)]
        anchor = 0.0
    return WorkerTrace(epoch_unix=anchor, events=events)


def load_fleet_dir(path) -> dict[str, WorkerData]:
    """Discover the fleet under ``path``: a directory of ``worker_*``
    subdirs (the elastic layout AND the collector layout — identical on
    purpose), or a single obs trio dir (treated as worker "0", so the
    fleet commands degrade gracefully to one process).  Raises
    FileNotFoundError with an operator-grade message otherwise."""
    p = Path(path)
    if not p.exists():
        raise FileNotFoundError(f"no such directory: {p}")
    subdirs = sorted(d for d in p.glob("worker_*") if d.is_dir())
    if subdirs:
        workers = {}
        for d in subdirs:
            w = load_worker_dir(d)
            workers[w.worker] = w
        return workers
    if (p / "metrics.jsonl").exists() or (p / "trace.json").exists():
        w = load_worker_dir(p, worker="0")
        return {w.worker: w}
    raise FileNotFoundError(
        f"{p} holds neither worker_* subdirs nor an obs artifact trio — "
        "point at the shared obs.dir of an elastic run, a collector "
        "--telemetry-dir, or one worker's obs dir"
    )


# ---------------------------------------------------------- clock alignment
def _fed_round_starts(trace: WorkerTrace) -> dict[int, float]:
    """round -> wall-clock start of the ``fed_round`` span anchored at it
    (chunked spans anchor at their first round)."""
    out: dict[int, float] = {}
    for e in trace.events:
        if e.get("name") != "fed_round" or e.get("ph") != "X":
            continue
        args = e.get("args", {})
        r = args.get("step_num")
        if r is None:
            continue
        wall = trace.epoch_unix + float(e.get("ts", 0.0)) / 1e6
        out.setdefault(int(r), wall)
    return out


def wire_edge_offsets(
    workers: dict[str, WorkerData],
) -> dict[str, dict[str, float]]:
    """Per-worker wire-measured clock offsets (seconds) toward each peer
    it exchanged enveloped requests with: ``{worker: {peer: offset_s}}``
    where ``offset_s`` is the PEER's clock minus the worker's — the
    windowed NTP-style estimate :mod:`fedrec_tpu.obs.wire` publishes as
    ``wire.clock_offset_ms{peer}``, read back from the last snapshot."""
    from fedrec_tpu.obs.report import _metric_values

    out: dict[str, dict[str, float]] = {}
    for wid, w in workers.items():
        snap = w.last_snapshot()
        if snap is None:
            continue
        edges: dict[str, float] = {}
        for row in _metric_values(snap, "wire.clock_offset_ms"):
            peer = (row.get("labels") or {}).get("peer")
            if peer is not None and "value" in row:
                edges[str(peer)] = float(row["value"]) / 1e3
        if edges:
            out[wid] = edges
    return out


def estimate_clock_offsets(
    workers: dict[str, WorkerData],
) -> dict[tuple[str, int], float]:
    """Per-(worker, incarnation) clock correction in seconds, to ADD to
    that incarnation's wall clock.

    Two alignment sources, in precedence order:

    1. **Round barrier** — every worker's ``fed_round`` N begins at the
       same barrier collective (the round-counter broadcast all members
       block on), so for each incarnation the MEDIAN of (reference
       start - this start) over shared rounds estimates its offset
       against the reference incarnation — the one with the most
       ``fed_round`` spans (stable tie-break by worker id).  Barrier
       alignment always wins where shared rounds exist.
    2. **Wire edges** — an incarnation sharing NO round with the
       reference (the async commit authority, the membership service, a
       worker that died pre-round) resolves through the NTP-style
       per-edge offsets :mod:`fedrec_tpu.obs.wire` measured
       (:func:`wire_edge_offsets`): a worker that measured its offset to
       an aligned hub adopts ``hub_correction + offset``, and a hub that
       only ever ANSWERED requests is placed at the median of
       ``client_correction - client_offset`` over its aligned clients.
       The graph is walked to a fixpoint, so a chain of edges aligns
       too.  Only incarnations the wire cannot reach keep correction 0
       (the raw ``epoch_unix`` wall anchor, the honest fallback)."""
    rounds_by: dict[tuple[str, int], dict[int, float]] = {}
    for wid, w in workers.items():
        for i, tr in enumerate(w.traces):
            rounds_by[(wid, i)] = _fed_round_starts(tr)
    ref_key = None
    for key in sorted(rounds_by):
        if ref_key is None or len(rounds_by[key]) > len(rounds_by[ref_key]):
            ref_key = key
    offsets: dict[tuple[str, int], float] = {}
    unaligned: set[tuple[str, int]] = set()
    ref_rounds = rounds_by.get(ref_key, {}) if ref_key is not None else {}
    for key, mine in rounds_by.items():
        shared = sorted(set(mine) & set(ref_rounds))
        if not shared or key == ref_key:
            offsets[key] = 0.0
            if key != ref_key:
                unaligned.add(key)
            continue
        deltas = sorted(ref_rounds[r] - mine[r] for r in shared)
        offsets[key] = deltas[len(deltas) // 2]  # median
    if not unaligned:
        return offsets
    edges = wire_edge_offsets(workers)
    if not edges:
        return offsets
    # worker-level corrections from barrier-aligned incarnations (the
    # incarnation with the most fed_round spans speaks for the worker)
    aligned: dict[str, float] = {}
    spans_of: dict[str, int] = {}
    for key, off in offsets.items():
        if key in unaligned:
            continue
        wid, _ = key
        n = len(rounds_by.get(key, {}))
        if wid not in aligned or n >= spans_of[wid]:
            aligned[wid] = off
            spans_of[wid] = n
    pending = {wid for wid, _ in unaligned if wid not in aligned}
    for _ in range(len(pending) + 1):
        placed: dict[str, float] = {}
        for wid in sorted(pending):
            cands = [
                aligned[p] + o
                for p, o in edges.get(wid, {}).items()
                if p in aligned
            ]
            cands += [
                aligned[c] - o
                for c, ce in edges.items()
                if c in aligned
                for p, o in ce.items()
                if p == wid
            ]
            if cands:
                cands.sort()
                placed[wid] = cands[len(cands) // 2]
        if not placed:
            break
        aligned.update(placed)
        pending -= set(placed)
    for key in unaligned:
        wid, _ = key
        if wid in aligned:
            offsets[key] = aligned[wid]
    return offsets


# ------------------------------------------------------------- merged trace
def build_fleet_trace(workers: dict[str, WorkerData]) -> dict:
    """ONE Chrome/Perfetto document over every worker's events: a track
    (pid) per worker with a ``process_name`` metadata header, timestamps
    re-based onto the fleet-aligned wall clock (coarse ``epoch_unix`` +
    the round-barrier offset refinement), membership/chaos instants
    riding along unchanged."""
    offsets = estimate_clock_offsets(workers)
    order = sorted(workers)
    pid_of = {wid: i + 1 for i, wid in enumerate(order)}
    aligned: list[tuple[float, dict]] = []
    t0: float | None = None
    for wid in order:
        w = workers[wid]
        for i, tr in enumerate(w.traces):
            corr = offsets.get((wid, i), 0.0)
            for e in tr.events:
                wall = tr.epoch_unix + float(e.get("ts", 0.0)) / 1e6 + corr
                if t0 is None or wall < t0:
                    t0 = wall
                ev = dict(e)
                ev["pid"] = pid_of[wid]
                args = dict(ev.get("args", {}))
                args.setdefault("worker", wid)
                if tr.tag:
                    args.setdefault("incarnation", tr.tag)
                ev["args"] = args
                aligned.append((wall, ev))
    t0 = t0 or 0.0
    events: list[dict] = []
    for wid in order:
        snap = workers[wid].last_snapshot() or {}
        fleet = snap.get("fleet", {})
        label = f"worker {wid}"
        if fleet.get("rank") is not None:
            label += f" (rank {fleet['rank']})"
        events.append({
            "name": "process_name", "ph": "M", "pid": pid_of[wid],
            "args": {"name": label},
        })
        events.append({
            "name": "process_sort_index", "ph": "M", "pid": pid_of[wid],
            "args": {"sort_index": pid_of[wid]},
        })
    for wall, ev in sorted(aligned, key=lambda p: p[0]):
        ev["ts"] = (wall - t0) * 1e6
        events.append(ev)
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {
            "producer": "fedrec_tpu.obs.fleet",
            "epoch_unix": t0,
            "workers": {wid: pid_of[wid] for wid in order},
            "clock_offsets_s": {
                f"{wid}/{i}": round(off, 6)
                for (wid, i), off in sorted(offsets.items())
            },
        },
    }


# ------------------------------------------------- critical-path attribution
def _round_intervals(
    tr: WorkerTrace, corr: float
) -> list[tuple[int, float, float, dict[str, float]]]:
    """(round, aligned start, aligned end, phase durations) per round
    covered by this incarnation's ``fed_round`` spans.  A rounds-in-jit
    chunk (``num_rounds`` > 1) is one dispatch: its wall interval is
    split evenly across its rounds and its phase work attributed to
    each covered round at 1/num_rounds — the same even attribution the
    Trainer's round-seconds histogram applies.

    Phase events are bucketed ONCE (sorted by start, window lookups by
    bisection): a rescans-per-span loop would be quadratic in trace
    size, and ``obs.trace_capacity`` defaults to 200k events."""
    from bisect import bisect_left, bisect_right

    spans: list[tuple[int, int, float, float]] = []
    phase_evs: list[tuple[float, str, float]] = []  # (start, name, dur_ms)
    for e in tr.events:
        if e.get("ph") != "X":
            continue
        name = e.get("name")
        if name == "fed_round":
            args = e.get("args", {})
            if args.get("step_num") is None:
                continue
            start = tr.epoch_unix + float(e.get("ts", 0.0)) / 1e6 + corr
            end = start + float(e.get("dur", 0.0)) / 1e6
            spans.append(
                (int(args["step_num"]),
                 max(int(args.get("num_rounds", 1)), 1), start, end)
            )
        elif name in ROUND_PHASES:
            s = tr.epoch_unix + float(e.get("ts", 0.0)) / 1e6 + corr
            phase_evs.append((s, name, float(e.get("dur", 0.0)) / 1e3))
    phase_evs.sort(key=lambda p: p[0])
    phase_starts = [p[0] for p in phase_evs]
    out: list[tuple[int, float, float, dict[str, float]]] = []
    for first, n, start, end in spans:
        phases: dict[str, float] = {}
        for i in range(bisect_left(phase_starts, start),
                       bisect_right(phase_starts, end)):
            _, name, dur_ms = phase_evs[i]
            phases[name] = phases.get(name, 0.0) + dur_ms
        per = (end - start) / n
        for i in range(n):
            out.append((
                first + i, start + i * per, start + (i + 1) * per,
                {k: v / n for k, v in phases.items()},
            ))
    return out


def attribute_critical_path(workers: dict[str, WorkerData]) -> list[dict]:
    """Per-round straggler attribution over the aligned fleet timeline.

    For each round any worker recorded, the worker whose ``fed_round``
    interval ENDS last gated the barrier (the next round's broadcast
    waits on the slowest member).  ``gate_ms`` is the straggler's
    MARGINAL delay — how much later it finished than the runner-up,
    i.e. the round-time saving if only this worker were fixed (the
    barrier would then release at the runner-up's end); ``phase`` is
    the gating worker's dominant round-work span (ms, from
    :data:`ROUND_PHASES`)."""
    offsets = estimate_clock_offsets(workers)
    per_round: dict[int, list[tuple[str, float, float, dict]]] = {}
    for wid, w in workers.items():
        for i, tr in enumerate(w.traces):
            for r, start, end, phases in _round_intervals(
                tr, offsets.get((wid, i), 0.0)
            ):
                per_round.setdefault(r, []).append((wid, start, end, phases))
    rows: list[dict] = []
    for r in sorted(per_round):
        entries = per_round[r]
        # one entry per worker: a replayed round keeps its LAST attempt
        by_worker: dict[str, tuple[str, float, float, dict]] = {}
        for ent in sorted(entries, key=lambda t: t[2]):
            by_worker[ent[0]] = ent
        ents = list(by_worker.values())
        crit = max(ents, key=lambda t: t[2])
        others = [e for e in ents if e[0] != crit[0]]
        gate_ms = (
            (crit[2] - max(e[2] for e in others)) * 1e3 if others else 0.0
        )
        phase = (
            max(crit[3], key=crit[3].get) if crit[3] else None
        )
        rows.append({
            "round": r,
            "critical_worker": crit[0],
            "round_ms": round((crit[2] - crit[1]) * 1e3, 3),
            "gate_ms": round(max(gate_ms, 0.0), 3),
            "phase": phase,
            "workers": {
                e[0]: round((e[2] - e[1]) * 1e3, 3) for e in ents
            },
        })
    return rows


# ------------------------------------------------------------- fleet report
def _snap_value(snap: dict | None, name: str, labels: dict | None = None):
    from fedrec_tpu.obs.report import snapshot_value

    return snapshot_value(snap, name, labels) if snap else None


def build_fleet_report(workers: dict[str, WorkerData]) -> dict:
    """The fleet's one-artifact answer: per-worker identity/epoch/rounds,
    the membership timeline (from the service's own artifacts when it
    wrote them), per-round critical-path attribution with per-worker
    times-on-critical-path totals, and per-worker DCN bytes."""
    from fedrec_tpu.obs.report import _metric_values

    report: dict[str, Any] = {"workers": {}}
    service_snap = None
    for wid in sorted(workers):
        w = workers[wid]
        snap = w.last_snapshot()
        fleet = (snap or {}).get("fleet", {})
        info: dict[str, Any] = {
            "rank": fleet.get("rank"),
            "membership_epoch": fleet.get(
                "membership_epoch", _snap_value(snap, "fed.membership_epoch")
            ),
            "incarnations": len(w.traces),
            "spans": sum(len(t.events) for t in w.traces),
            "snapshots": len(w.snapshots),
        }
        rounds = _snap_value(snap, "train.rounds_total")
        if rounds is not None:
            info["rounds_total"] = rounds
        loss = _snap_value(snap, "train.round_loss")
        if loss is not None:
            info["last_loss"] = loss
        # the service registers its counters even before any shrink, so
        # detection keys on registration, not on a nonzero value
        if "fed.membership_shrinks_total" in (snap or {}).get("metrics", {}):
            service_snap = snap
            info["role"] = "membership_service"
        report["workers"][wid] = info

    if service_snap is not None:
        mem: dict[str, Any] = {}
        for key, name in (
            ("epoch", "fed.membership_epoch"),
            ("world", "fed.membership_world"),
            ("shrinks", "fed.membership_shrinks_total"),
            ("rejoins", "fed.membership_rejoins_total"),
            ("lease_misses", "fed.membership_lease_misses_total"),
        ):
            v = _snap_value(service_snap, name)
            if v is not None:
                mem[key] = v
        # the epoch timeline from the service's formation instants
        timeline = []
        for wid, w in workers.items():
            if report["workers"][wid].get("role") != "membership_service":
                continue
            for tr in w.traces:
                for e in tr.events:
                    if e.get("name") == "membership_epoch_formed":
                        a = e.get("args", {})
                        timeline.append({
                            "epoch": a.get("epoch"), "world": a.get("world"),
                        })
        if timeline:
            mem["epoch_history"] = timeline
        report["membership"] = mem

    rounds = attribute_critical_path(workers)
    if rounds:
        report["rounds"] = rounds
        counts: dict[str, int] = {}
        gated: dict[str, float] = {}
        for row in rounds:
            c = row["critical_worker"]
            counts[c] = counts.get(c, 0) + 1
            gated[c] = gated.get(c, 0.0) + row["gate_ms"]
        report["critical_path"] = {
            wid: {"rounds": counts[wid], "gate_ms": round(gated[wid], 3)}
            for wid in sorted(counts)
        }

    dcn: dict[str, Any] = {}
    for wid in sorted(workers):
        snap = workers[wid].last_snapshot()
        if snap is None:
            continue
        up = {
            row["labels"].get("path", "?"): row["value"]
            for row in _metric_values(snap, "fed.dcn_bytes_up_total")
            if "value" in row and row["value"] > 0
        }
        if up:
            dcn[wid] = {"bytes_up": up}
            down = {
                row["labels"].get("path", "?"): row["value"]
                for row in _metric_values(snap, "fed.dcn_bytes_down_total")
                if "value" in row and row["value"] > 0
            }
            if down:
                dcn[wid]["bytes_down"] = down
    if dcn:
        report["dcn_bytes"] = dcn

    # ---- quality (obs.quality): per-worker corpus AUC, the worst eval
    # slice, calibration and serving drift — the fleet view of the sliced
    # eval telemetry, compacted from the ONE shared extraction
    # (report.quality_detail_from_snapshot). Silent when no worker
    # published quality gauges.
    from fedrec_tpu.obs.report import quality_detail_from_snapshot

    quality: dict[str, Any] = {}
    for wid in sorted(workers):
        snap = workers[wid].last_snapshot()
        if snap is None:
            continue
        detail = quality_detail_from_snapshot(snap)
        if not detail:
            continue
        qw: dict[str, Any] = {}
        slices_d = {
            k: m for k, m in detail.get("slices", {}).items() if "auc" in m
        }
        if "all" in slices_d:
            qw["auc"] = slices_d["all"]["auc"]
        named = {k: m["auc"] for k, m in slices_d.items() if k != "all"}
        if named:
            worst = min(named, key=named.get)
            qw["worst_slice"] = worst
            qw["worst_slice_auc"] = named[worst]
        for key in ("ece", "quality_outlier_client_evals"):
            if key in detail:
                qw[key] = detail[key]
        drift = detail.get("drift", {})
        for key, src in (
            ("drift_rank_churn", "rank_churn"),
            ("drift_score_shift_mean", "score_shift_mean"),
        ):
            if src in drift:
                qw[key] = drift[src]
        if qw:
            quality[wid] = qw
    if quality:
        report["quality"] = quality

    # ---- perf (obs.perf): per-worker last-round MFU/throughput and the
    # dominant roofline verdict — the fleet view of the live efficiency
    # gauges, compacted from the ONE shared extraction
    # (report.perf_detail_from_snapshot). Silent when no worker published
    # perf gauges.
    from fedrec_tpu.obs.report import perf_detail_from_snapshot

    perf: dict[str, Any] = {}
    for wid in sorted(workers):
        snap = workers[wid].last_snapshot()
        if snap is None:
            continue
        detail = perf_detail_from_snapshot(snap)
        if not detail:
            continue
        pw = {
            key: detail[key]
            for key in (
                "samples_per_sec", "mfu", "hbm_fraction", "verdict",
                "host_ms_per_step", "dispatch_ms_per_step",
            )
            if key in detail
        }
        if pw:
            perf[wid] = pw
    if perf:
        report["perf"] = perf

    # ---- aggregation (fedrec_tpu.agg): the async commit authority's
    # quorum/staleness accounting and each worker's marginal commit gate.
    # gate_ms BEFORE going async is the barrier critical path ("Critical
    # path" above: the slowest worker gates everyone); AFTER it is
    # agg.worker_gate_ms — a straggler that never closes a quorum stays
    # ~0 there. Silent when no worker published agg.* metrics.
    agg: dict[str, Any] = {}
    for wid in sorted(workers):
        snap = workers[wid].last_snapshot()
        if snap is None:
            continue
        if not any(
            k.startswith("agg.") for k in (snap.get("metrics") or {})
        ):
            continue
        aw: dict[str, Any] = {}
        for key, name in (
            ("commits", "agg.commits_total"),
            ("late_folds", "agg.late_folds_total"),
            ("stale_drops", "agg.stale_drops_total"),
            ("staleness", "agg.staleness"),
            ("quorum_wait_ms", "agg.quorum_wait_ms"),
            ("gate_saved_ms", "agg.gate_saved_ms"),
            ("tier_reduce_ms", "agg.tier_reduce_ms"),
            ("commit_fold_ms", "agg.commit_fold_ms"),
            ("buffer_pending", "agg.buffer_pending"),
            ("pushes", "agg.pushes_total"),
            ("global_version", "agg.global_version"),
        ):
            v = _snap_value(snap, name)
            if v is not None:
                aw[key] = v
        gate = {
            row["labels"].get("worker", "?"): row["value"]
            for row in _metric_values(snap, "agg.worker_gate_ms")
            if "value" in row
        }
        if gate:
            # only the commit authority holds the per-worker gate cells
            aw["worker_gate_ms"] = gate
            aw["role"] = "agg_server"
        if aw:
            agg[wid] = aw
    if agg:
        report["agg"] = agg

    # ---- wire (obs.wire): per-edge request/RTT telemetry, the measured
    # clock-offset table, and the queue/wire/fold decomposition of async
    # commit latency. Silent when no worker published wire.* metrics.
    wire_edges: dict[str, list[dict]] = {}
    wire_offsets: dict[str, dict[str, float]] = {}
    for wid in sorted(workers):
        snap = workers[wid].last_snapshot()
        if snap is None:
            continue
        edges: dict[tuple[str, str], dict[str, Any]] = {}

        def _edge(lbl: dict) -> dict:
            key = (str(lbl.get("peer", "?")), str(lbl.get("op", "?")))
            return edges.setdefault(key, {"peer": key[0], "op": key[1]})

        for name, fld in (
            ("wire.requests_total", "requests"),
            ("wire.errors_total", "errors"),
            ("wire.reconnects_total", "reconnects"),
            ("wire.bytes_sent_total", "bytes_sent"),
            ("wire.bytes_recvd_total", "bytes_recvd"),
        ):
            for row in _metric_values(snap, name):
                if "value" in row:
                    _edge(row.get("labels") or {})[fld] = row["value"]
        for name, fld in (
            ("wire.rtt_ms", "rtt_ms"),
            ("wire.server_ms", "server_ms"),
        ):
            for row in _metric_values(snap, name):
                if row.get("count"):
                    _edge(row.get("labels") or {})[fld] = round(
                        row["sum"] / row["count"], 3
                    )
        if edges:
            wire_edges[wid] = [edges[k] for k in sorted(edges)]
        offs = {
            str((row.get("labels") or {}).get("peer", "?")):
                round(row["value"], 3)
            for row in _metric_values(snap, "wire.clock_offset_ms")
            if "value" in row
        }
        if offs:
            wire_offsets[wid] = offs
    if wire_edges or wire_offsets:
        wire: dict[str, Any] = {}
        if wire_edges:
            wire["edges"] = wire_edges
            slowest = None
            for wid, rows in wire_edges.items():
                for e in rows:
                    if "rtt_ms" in e and (
                        slowest is None or e["rtt_ms"] > slowest["rtt_ms"]
                    ):
                        slowest = {
                            "worker": wid, "peer": e["peer"],
                            "op": e["op"], "rtt_ms": e["rtt_ms"],
                        }
            if slowest:
                wire["slowest_edge"] = slowest
        if wire_offsets:
            wire["offsets_ms"] = wire_offsets
        # queue vs wire vs fold: the commit authority's quorum wait and
        # fold time, plus each pushing worker's transport share (its
        # push edge's RTT minus the echoed server handling time)
        queue_ms = fold_ms = None
        for aw in (report.get("agg") or {}).values():
            if aw.get("role") == "agg_server":
                queue_ms = aw.get("quorum_wait_ms")
                fold_ms = aw.get("commit_fold_ms")
        decomp_edges: dict[str, dict[str, Any]] = {}
        for wid, rows in wire_edges.items():
            for e in rows:
                if e["op"] == "push" and "rtt_ms" in e:
                    srv = e.get("server_ms", 0.0)
                    decomp_edges[wid] = {
                        "peer": e["peer"],
                        "rtt_ms": e["rtt_ms"],
                        "server_ms": srv,
                        "wire_ms": round(max(e["rtt_ms"] - srv, 0.0), 3),
                    }
        if queue_ms is not None or fold_ms is not None or decomp_edges:
            decomp: dict[str, Any] = {}
            if queue_ms is not None:
                decomp["queue_ms"] = queue_ms
            if fold_ms is not None:
                decomp["fold_ms"] = fold_ms
            if decomp_edges:
                decomp["edges"] = decomp_edges
            wire["commit_decomposition"] = decomp
        report["wire"] = wire

    # ---- alerts (obs.watch): every worker's {"kind":"alert"} lifecycle
    # records, the fleet rules' worker_fleet log included. The active set
    # is computed PER worker, so two workers' identical keys (each runs
    # its own slo:round_time) keep independent lifecycles.
    from fedrec_tpu.obs.watch import active_alerts, alert_records

    timeline: list[dict] = []
    active: list[dict] = []
    for wid in sorted(workers):
        recs = alert_records(workers[wid].records)
        for r in recs:
            r.setdefault("labels", {}).setdefault("worker", wid)
        timeline.extend(recs)
        active.extend(active_alerts(recs))
    if timeline:
        timeline.sort(key=lambda r: r.get("ts", 0.0))
        report["alerts"] = {
            "transitions": len(timeline),
            "active": active,
            "recent": timeline[-12:],
        }
    return report


def render_fleet_text(report: dict) -> str:
    """Human-readable fleet report (the ``fedrec-obs fleet`` output)."""
    lines = ["# fedrec_tpu fleet report", ""]
    lines.append("## Workers")
    header = f"{'worker':<14} {'rank':>4} {'epoch':>5} {'rounds':>6} " \
             f"{'spans':>7} {'snaps':>5}"
    lines.append(header)
    for wid, info in report.get("workers", {}).items():
        rank = info.get("rank")
        epoch = info.get("membership_epoch")
        label = wid + ("*" if info.get("role") == "membership_service" else "")
        lines.append(
            f"{label:<14} {('-' if rank is None else int(rank)):>4} "
            f"{('-' if epoch is None else int(epoch)):>5} "
            f"{int(info.get('rounds_total', 0)):>6} "
            f"{int(info.get('spans', 0)):>7} {int(info.get('snapshots', 0)):>5}"
        )
    if any(
        i.get("role") == "membership_service"
        for i in report.get("workers", {}).values()
    ):
        lines.append("(* = membership service)")
    lines.append("")
    al = report.get("alerts")
    if al:
        lines.append("## Alerts")
        lines.append(f"transitions: {int(al.get('transitions', 0))}")
        if al.get("active"):
            lines.append(f"STILL FIRING ({len(al['active'])}):")
            for r in al["active"]:
                w = (r.get("labels") or {}).get("worker", "?")
                lines.append(
                    f"  [{r.get('severity', '?')}] worker {w} "
                    f"{r.get('key', '?')}: {r.get('summary', '')}"
                )
        else:
            lines.append("active: none (every fired alert resolved)")
        for r in (al.get("recent") or [])[-6:]:
            w = (r.get("labels") or {}).get("worker", "?")
            lines.append(
                f"  {r.get('event', '?'):<9} worker {w} {r.get('key', '?')}"
            )
        lines.append("")
    mem = report.get("membership")
    if mem:
        lines.append("## Membership")
        lines.append(
            f"epoch: {int(mem.get('epoch', -1))}, "
            f"world: {int(mem.get('world', 0))}, "
            f"shrinks: {int(mem.get('shrinks', 0))}, "
            f"rejoins: {int(mem.get('rejoins', 0))}, "
            f"lease misses: {int(mem.get('lease_misses', 0))}"
        )
        hist = mem.get("epoch_history")
        if hist:
            lines.append(
                "epoch history: "
                + " -> ".join(
                    f"e{h.get('epoch')}@{h.get('world')}w" for h in hist
                )
            )
        lines.append("")
    rounds = report.get("rounds")
    if rounds:
        lines.append("## Critical path (per round)")
        lines.append(
            f"{'round':>5} {'worker':<12} {'round_ms':>10} {'gate_ms':>9} "
            f"{'phase':<12}"
        )
        for row in rounds:
            lines.append(
                f"{row['round']:>5} {row['critical_worker']:<12} "
                f"{row['round_ms']:>10} {row['gate_ms']:>9} "
                f"{row.get('phase') or '-':<12}"
            )
        lines.append("")
    crit = report.get("critical_path")
    if crit:
        lines.append("## Times on critical path")
        for wid, c in crit.items():
            lines.append(
                f"worker {wid}: {c['rounds']} round(s), "
                f"{c['gate_ms']:.1f} ms gated"
            )
        lines.append("")
    dcn = report.get("dcn_bytes")
    if dcn:
        lines.append("## DCN bytes by worker")

        def _mb(n: float) -> str:
            return f"{n / (1024 * 1024):.2f} MB"

        for wid, d in dcn.items():
            up = ", ".join(
                f"{p}={_mb(v)}" for p, v in sorted(d["bytes_up"].items())
            )
            lines.append(f"worker {wid}: up {up}")
        lines.append("")
    quality = report.get("quality")
    if quality:
        lines.append("## Quality by worker")
        for wid, qw in quality.items():
            parts = []
            if "auc" in qw:
                parts.append(f"auc={qw['auc']:.4f}")
            if "worst_slice" in qw:
                parts.append(
                    f"worst slice {qw['worst_slice']}="
                    f"{qw['worst_slice_auc']:.4f}"
                )
            if "ece" in qw:
                parts.append(f"ece={qw['ece']:.4f}")
            if "drift_rank_churn" in qw:
                parts.append(f"drift churn={qw['drift_rank_churn']:.3f}")
            if "quality_outlier_client_evals" in qw:
                parts.append(
                    f"outlier client-evals="
                    f"{int(qw['quality_outlier_client_evals'])}"
                )
            lines.append(f"worker {wid}: " + ", ".join(parts))
        lines.append("")
    perf = report.get("perf")
    if perf:
        lines.append("## Perf by worker")
        for wid, pw in perf.items():
            parts = []
            if "samples_per_sec" in pw:
                parts.append(f"{pw['samples_per_sec']:.1f} samples/s")
            if "mfu" in pw:
                parts.append(f"mfu={pw['mfu']:.4f}")
            if "hbm_fraction" in pw:
                parts.append(f"hbm={pw['hbm_fraction']:.3f}")
            if "host_ms_per_step" in pw:
                parts.append(f"host={pw['host_ms_per_step']:.2f}ms/step")
            if "verdict" in pw:
                parts.append(f"verdict={pw['verdict']}")
            lines.append(f"worker {wid}: " + ", ".join(parts))
        lines.append("")
    agg = report.get("agg")
    if agg:
        lines.append("## Aggregation")
        for wid, aw in agg.items():
            parts = []
            if aw.get("role") == "agg_server":
                parts.append("commit authority")
            for key, fmt in (
                ("commits", "commits={:d}"),
                ("global_version", "version={:d}"),
                ("pushes", "pushes={:d}"),
                ("late_folds", "late_folds={:d}"),
                ("stale_drops", "stale_drops={:d}"),
                ("buffer_pending", "pending={:d}"),
            ):
                if key in aw:
                    parts.append(fmt.format(int(aw[key])))
            for key, fmt in (
                ("staleness", "staleness={:.2f}"),
                ("quorum_wait_ms", "quorum_wait={:.0f}ms"),
                ("gate_saved_ms", "gate_saved={:.0f}ms"),
                ("tier_reduce_ms", "tier_reduce={:.1f}ms"),
            ):
                if key in aw:
                    parts.append(fmt.format(aw[key]))
            lines.append(f"worker {wid}: " + ", ".join(parts))
        # the before/after gate panel: barrier gate_ms (critical path,
        # above) vs each worker's async marginal gate — the async win is
        # the straggler's row reading ~0 here
        gates = {
            w: g
            for aw in agg.values()
            for w, g in (aw.get("worker_gate_ms") or {}).items()
        }
        if gates:
            crit = report.get("critical_path") or {}
            lines.append("")
            lines.append("gate_ms before (sync barrier) -> after (async commit):")
            for w in sorted(gates):
                before = crit.get(w, {}).get("gate_ms")
                before_s = "-" if before is None else f"{before:.1f}"
                lines.append(
                    f"  worker {w}: {before_s} -> {gates[w]:.1f} ms"
                )
        lines.append("")
    wire = report.get("wire")
    if wire:
        lines.append("## Wire")
        edges = wire.get("edges")
        if edges:
            lines.append(
                f"{'worker':<12} {'peer':<12} {'op':<10} {'reqs':>6} "
                f"{'errs':>5} {'rtt_ms':>9} {'srv_ms':>9}"
            )
            for wid, rows in edges.items():
                for e in rows:
                    rtt = e.get("rtt_ms")
                    srv = e.get("server_ms")
                    lines.append(
                        f"{wid:<12} {e['peer']:<12} {e['op']:<10} "
                        f"{int(e.get('requests', 0)):>6} "
                        f"{int(e.get('errors', 0)):>5} "
                        f"{('-' if rtt is None else format(rtt, '.2f')):>9} "
                        f"{('-' if srv is None else format(srv, '.2f')):>9}"
                    )
        offs = wire.get("offsets_ms")
        if offs:
            lines.append("")
            lines.append("clock offsets (peer minus worker, ms):")
            for wid, table in offs.items():
                parts = ", ".join(
                    f"{p}={v:+.1f}" for p, v in sorted(table.items())
                )
                lines.append(f"  worker {wid}: {parts}")
        slow = wire.get("slowest_edge")
        if slow:
            lines.append("")
            lines.append(
                f"slowest edge: worker {slow['worker']} -> {slow['peer']} "
                f"({slow['op']}) at {slow['rtt_ms']:.2f} ms mean RTT"
            )
        decomp = wire.get("commit_decomposition")
        if decomp:
            lines.append("")
            lines.append("async commit latency (queue vs wire vs fold):")
            head = []
            if "queue_ms" in decomp:
                head.append(
                    f"queue(quorum wait)={decomp['queue_ms']:.1f}ms"
                )
            if "fold_ms" in decomp:
                head.append(f"fold={decomp['fold_ms']:.2f}ms")
            if head:
                lines.append("  " + ", ".join(head))
            for wid, d in (decomp.get("edges") or {}).items():
                lines.append(
                    f"  worker {wid} -> {d['peer']}: "
                    f"wire={d['wire_ms']:.2f}ms "
                    f"(rtt {d['rtt_ms']:.2f} - server {d['server_ms']:.2f})"
                )
        lines.append("")
    if not report.get("workers"):
        lines.append("(no workers found)")
    return "\n".join(lines)


# ------------------------------------------------------------- collector CLI
def main(argv: list[str] | None = None) -> None:
    """Standalone fleet telemetry collector: ``python -m
    fedrec_tpu.obs.fleet HOST:PORT --dir D``.  With ``--watch`` the
    fleet-level watch rules (:class:`fedrec_tpu.obs.watch.FleetRules`)
    evaluate per push and their alert records land in
    ``D/worker_fleet/metrics.jsonl`` — read by ``fedrec-obs alerts D``
    like any other worker's log.  (The membership service offers the
    same sink on its own port via ``--telemetry-dir``.)"""
    import argparse

    parser = argparse.ArgumentParser(
        description="standalone fleet telemetry collector"
    )
    parser.add_argument("address", help="host:port to listen on")
    parser.add_argument(
        "--dir", required=True, help="collector artifact directory"
    )
    parser.add_argument(
        "--watch", action="store_true",
        help="evaluate fleet-level watch rules on every push "
             "(straggler / quorum-wait growth / stalled commit)",
    )
    parser.add_argument(
        "--target-world", type=int, default=0,
        help="world size the fleet:world_below_target rule compares "
             "against (0 disables the rule)",
    )
    parser.add_argument(
        "--straggler-factor", type=float, default=None,
        help="override obs.watch.fleet_straggler_factor for the "
             "persistent-straggler rule",
    )
    parser.add_argument(
        "--straggler-evals", type=int, default=None,
        help="override obs.watch.fleet_straggler_evals (consecutive "
             "breaching pushes before the straggler alert fires)",
    )
    parser.add_argument(
        "--jsonl-max-mb", type=float, default=256.0,
        help="per-worker log rotation bound",
    )
    args = parser.parse_args(argv)
    host, _, port = args.address.rpartition(":")
    collector = TelemetryCollector(args.dir, jsonl_max_mb=args.jsonl_max_mb)
    if args.watch:
        from fedrec_tpu.config import WatchConfig
        from fedrec_tpu.obs.watch import FleetRules

        wcfg = WatchConfig()
        if args.straggler_factor is not None:
            wcfg.fleet_straggler_factor = args.straggler_factor
        if args.straggler_evals is not None:
            wcfg.fleet_straggler_evals = args.straggler_evals
        fleet_dir = Path(args.dir) / "worker_fleet"
        fleet_dir.mkdir(parents=True, exist_ok=True)
        collector.rules = FleetRules(
            wcfg,
            target_world=args.target_world,
            jsonl_path=fleet_dir / "metrics.jsonl",
        )
    server = CollectorServer(collector, host or "127.0.0.1", int(port))
    server.start()
    print(
        f"[collector] listening on {server.address} dir={args.dir}"
        + (" watch=on" if args.watch else ""),
        flush=True,
    )
    try:
        while True:
            time.sleep(2.0)
    except KeyboardInterrupt:
        pass
    finally:
        server.stop()


if __name__ == "__main__":
    main()
