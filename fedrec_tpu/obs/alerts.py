"""Alert lifecycle engine: pending → firing → resolved, one record shape.

Before this module the stack had four bespoke trigger idioms — the
health monitor printed outlier lines, the quality digest printed its
own, the serving drift probe only set gauges, and the perf efficiency-
drop trigger armed a capture through a private flag.  None of them had
a lifecycle: nothing ever *resolved*, nothing deduplicated, and a
flapping signal spammed its surface on every evaluation.  This module
is the ONE path every alert now takes (:mod:`fedrec_tpu.obs.watch`
feeds it SLO burn-rate breaches, anomaly detections, and the unified
legacy triggers):

* **Lifecycle** — ``observe(key, breached)`` at evaluation cadence
  drives each keyed alert through pending (``pending_for`` consecutive
  breached evaluations before firing — the multi-evaluation
  confirmation that keeps one bad sample from paging), firing, and
  resolved (``resolve_after`` consecutive healthy evaluations).
* **Dedup** — a firing alert that keeps breaching emits nothing new;
  the transition is the event, not the state.
* **Flap suppression** — ``flap_max`` fire cycles within
  ``flap_window`` evaluations mute further transition records for that
  key (counted on ``alert.flaps_suppressed_total``), so an oscillating
  signal cannot flood the log.
* **Emission** — every transition lands everywhere at once: the
  ``alert.*`` registry instruments, a ``{"kind": "alert"}`` JSONL
  record riding the existing event log + rotation, a tracer instant
  (inside whatever span — ``fed_round`` on the Trainer — is open), and
  any subscribed callbacks (the perf drop-capture arms off one).

The module imports no JAX (the obs package contract) and never raises
out of an emission path — alerting must not take down the host.
Metric catalogue: ``docs/OBSERVABILITY.md`` §11; operator runbook for a
firing SLO: ``docs/OPERATIONS.md`` §7g.
"""

from __future__ import annotations

import json
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable

from fedrec_tpu.obs.registry import MetricsRegistry, get_registry

SEVERITIES = ("info", "warning", "critical")

# transition records kept for FleetPusher catch-up slicing; beyond this
# the oldest are dropped and a late pusher simply misses them (the JSONL
# log remains the lossless record)
_RECORD_CAP = 4096


@dataclass
class Alert:
    """One keyed alert's live state."""

    key: str
    severity: str = "warning"
    summary: str = ""
    labels: dict[str, Any] = field(default_factory=dict)
    state: str = "pending"           # pending | firing | resolved
    value: float | None = None
    threshold: float | None = None
    first_breach_unix: float | None = None
    fired_unix: float | None = None
    resolved_unix: float | None = None
    breach_evals: int = 0
    clear_evals: int = 0
    fire_count: int = 0              # times this key fired (dedup counter)
    suppressed: bool = False         # currently flap-suppressed

    def to_dict(self) -> dict[str, Any]:
        return {
            "key": self.key,
            "severity": self.severity,
            "summary": self.summary,
            "labels": dict(self.labels),
            "state": self.state,
            "value": self.value,
            "threshold": self.threshold,
            "first_breach_unix": self.first_breach_unix,
            "fired_unix": self.fired_unix,
            "resolved_unix": self.resolved_unix,
            "fire_count": self.fire_count,
            "suppressed": self.suppressed,
        }


class AlertEngine:
    """The lifecycle state machine + every emission surface.

    ``observe()`` is the only mutation path; :mod:`fedrec_tpu.obs.watch`
    calls it once per (key, evaluation).  Per-call ``pending_for`` /
    ``resolve_after`` overrides let pulse-style triggers (anomaly,
    health outlier) fire on the first breached evaluation while SLO
    breaches keep the configured confirmation count.
    """

    def __init__(
        self,
        registry: MetricsRegistry | None = None,
        tracer: Any = None,
        *,
        pending_for: int = 2,
        resolve_after: int = 3,
        flap_max: int = 3,
        flap_window: int = 20,
        history: int = 256,
        jsonl_path=None,
        jsonl_max_mb: float = 0.0,
    ):
        self.registry = registry or get_registry()
        if tracer is None:
            from fedrec_tpu.obs.tracing import get_tracer

            tracer = get_tracer()
        self.tracer = tracer
        self.pending_for = max(int(pending_for), 1)
        self.resolve_after = max(int(resolve_after), 1)
        self.flap_max = max(int(flap_max), 0)
        self.flap_window = max(int(flap_window), 1)
        self.jsonl_path = jsonl_path
        self.jsonl_max_mb = float(jsonl_max_mb)
        self._alerts: dict[str, Alert] = {}
        self._history: deque[dict] = deque(maxlen=max(int(history), 1))
        # per-key eval counter + fire-transition eval indices (flap window)
        self._evals: dict[str, int] = {}
        self._fires: dict[str, deque[int]] = {}
        self._subscribers: list[Callable[[Alert, str], None]] = []
        # transition records for the FleetPusher envelope: (offset, list)
        self._records: list[dict] = []
        self._records_offset = 0
        self._c_transitions = self.registry.counter(
            "alert.transitions_total",
            "alert lifecycle transitions performed, labeled by the state "
            "entered (firing/resolved)",
            labels=("state",),
        )
        self._g_firing = self.registry.gauge(
            "alert.firing", "alerts currently in the firing state"
        )
        self._c_flaps = self.registry.counter(
            "alert.flaps_suppressed_total",
            "fire transitions muted by flap suppression (the key exceeded "
            "flap_max fire cycles within flap_window evaluations)",
        )

    # ------------------------------------------------------------ observe
    def observe(
        self,
        key: str,
        breached: bool,
        *,
        severity: str = "warning",
        summary: str = "",
        labels: dict[str, Any] | None = None,
        value: float | None = None,
        threshold: float | None = None,
        pending_for: int | None = None,
        resolve_after: int | None = None,
    ) -> Alert | None:
        """Advance ``key``'s lifecycle with one evaluation's verdict;
        returns the live alert (None once fully inactive)."""
        need_fire = max(int(pending_for or self.pending_for), 1)
        need_clear = max(int(resolve_after or self.resolve_after), 1)
        self._evals[key] = self._evals.get(key, 0) + 1
        a = self._alerts.get(key)
        if breached:
            if a is None or a.state == "resolved":
                a = Alert(key=key)
                self._alerts[key] = a
                a.first_breach_unix = time.time()
            a.severity = severity
            a.summary = summary or a.summary
            a.labels = dict(labels or a.labels)
            a.value = value
            a.threshold = threshold
            a.clear_evals = 0
            a.breach_evals += 1
            if a.state == "pending" and a.breach_evals >= need_fire:
                self._fire(a)
            return a
        if a is None:
            return None
        a.breach_evals = 0
        if a.state == "pending":
            # a pending alert that cleared never fired: silently drop
            del self._alerts[key]
            return None
        if a.state == "firing":
            a.clear_evals += 1
            if a.clear_evals >= need_clear:
                self._resolve(a)
        return a

    # -------------------------------------------------------- transitions
    def _flapping(self, key: str) -> bool:
        if self.flap_max <= 0:
            return False
        now = self._evals.get(key, 0)
        fires = self._fires.setdefault(key, deque())
        while fires and fires[0] <= now - self.flap_window:
            fires.popleft()
        return len(fires) >= self.flap_max

    def _fire(self, a: Alert) -> None:
        a.state = "firing"
        a.fired_unix = time.time()
        a.resolved_unix = None
        a.fire_count += 1
        suppressed = self._flapping(a.key)
        self._fires.setdefault(a.key, deque()).append(self._evals.get(a.key, 0))
        a.suppressed = suppressed
        if suppressed:
            self._c_flaps.inc()
            self._refresh_firing_gauge()
            return
        self._c_transitions.inc(state="firing")
        self._refresh_firing_gauge()
        self._emit(a, "firing")

    def _resolve(self, a: Alert) -> None:
        suppressed = a.suppressed
        a.state = "resolved"
        a.resolved_unix = time.time()
        a.suppressed = False
        self._history.append(a.to_dict())
        del self._alerts[a.key]
        self._refresh_firing_gauge()
        if suppressed:
            return  # a muted fire resolves silently too — no half-pairs
        self._c_transitions.inc(state="resolved")
        self._emit(a, "resolved")

    def _refresh_firing_gauge(self) -> None:
        self._g_firing.set(float(sum(
            1 for x in self._alerts.values() if x.state == "firing"
        )))

    # ----------------------------------------------------------- emission
    def _emit(self, a: Alert, event: str) -> None:
        record = {
            "kind": "alert",
            "event": event,
            "ts": time.time(),
            **{k: v for k, v in a.to_dict().items() if v is not None},
        }
        ctx = self.registry.context
        if ctx.get("worker") is not None and "worker" not in record["labels"]:
            record["labels"]["worker"] = ctx["worker"]
        self._records.append(record)
        if len(self._records) > _RECORD_CAP:
            drop = len(self._records) - _RECORD_CAP
            del self._records[:drop]
            self._records_offset += drop
        if self.jsonl_path is not None:
            try:
                from fedrec_tpu.obs.report import rotate_jsonl

                if self.jsonl_max_mb:
                    rotate_jsonl(self.jsonl_path, self.jsonl_max_mb)
                with open(self.jsonl_path, "a") as f:
                    f.write(json.dumps(record) + "\n")
            except OSError:
                pass  # alerting must not take down the host
        try:
            self.tracer.instant(
                "alert", key=a.key, event=event, severity=a.severity,
                summary=a.summary,
            )
        except Exception:  # noqa: BLE001 — emission is best-effort
            pass
        for fn in list(self._subscribers):
            try:
                fn(a, event)
            except Exception:  # noqa: BLE001 — a bad subscriber must not
                pass           # block the others or the lifecycle

    def subscribe(self, fn: Callable[[Alert, str], None]) -> None:
        """``fn(alert, event)`` runs on every unsuppressed transition —
        the hook the perf drop-capture arms off."""
        if fn not in self._subscribers:
            self._subscribers.append(fn)

    # ----------------------------------------------------------- surfaces
    def active(self) -> list[dict]:
        """Pending + firing alerts, firing first, newest breach first."""
        order = {"firing": 0, "pending": 1}
        return [
            a.to_dict() for a in sorted(
                self._alerts.values(),
                key=lambda x: (order.get(x.state, 2),
                               -(x.first_breach_unix or 0.0)),
            )
        ]

    def firing(self) -> list[dict]:
        return [a.to_dict() for a in self._alerts.values()
                if a.state == "firing"]

    def history(self) -> list[dict]:
        """Resolved alerts, oldest first (bounded by ``history``)."""
        return list(self._history)

    def records_since(self, index: int) -> tuple[list[dict], int]:
        """Transition records appended at/after absolute ``index`` —
        the FleetPusher's catch-up slice; returns (records, next_index)."""
        start = max(index - self._records_offset, 0)
        out = self._records[start:]
        return out, self._records_offset + len(self._records)

    def snapshot_state(self) -> dict:
        """The serving admin ``{"cmd": "alerts"}`` payload shape."""
        return {"active": self.active(), "recent": self.history()}
