"""Host-side span tracer emitting Chrome-trace / Perfetto JSON.

``jax.profiler`` answers "what did the DEVICE do" (XLA ops, HBM, MXU
occupancy); it says nothing about the host-side round structure — batch
build vs H2D vs compiled dispatch vs aggregation vs eval — or the
serving request lifecycle (enqueue -> batch -> dispatch -> reply).  This
tracer records those as wall-clock spans and writes them in the Chrome
trace event format (``{"traceEvents": [...]}``), which both
``chrome://tracing`` and https://ui.perfetto.dev load directly.

Correlating host and device: the Trainer wraps every round (or
rounds-in-jit chunk) in BOTH a host span here and a
``jax.profiler.StepTraceAnnotation("fed_round", step_num=...)``, so when
a device trace is captured (``train.profile=true``) the XLA steps carry
the same round numbers as the host spans.

Properties:

* **Cheap when idle**: recording a span is a clock read + a list append
  under a lock (~1 us); there is no I/O until ``save()``.
* **Bounded**: at most ``capacity`` events are kept (earliest win —
  the round structure of a run's HEAD is worth more than its tail);
  everything past that increments ``dropped`` and the count is stamped
  into the saved file's ``otherData``.
* **Timestamps are monotonic** (``time.perf_counter`` relative to the
  tracer's epoch, in microseconds) and ``save()`` sorts events, so the
  exported ``ts`` sequence is non-decreasing — the schema property the
  tests pin.

Spans whose duration was measured on a different clock (e.g. the
batcher's ``time.monotonic`` enqueue stamps) use :meth:`Tracer.add_span`
with an explicit duration; only the END is placed on the tracer clock.
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from typing import Any, Iterator


class Tracer:
    """Bounded in-memory recorder of Chrome-trace events."""

    def __init__(self, capacity: int = 200_000, clock=time.perf_counter):
        self.capacity = int(capacity)
        # enabled=False makes every record a no-op that also skips the drop
        # counter — the switch for processes that will never save a trace
        # (e.g. fedrec-serve without --obs-dir), so per-request spans cost
        # neither memory nor lock traffic there
        self.enabled = True
        self._clock = clock
        self._t0 = clock()
        self._epoch_unix = time.time()
        self._lock = threading.Lock()
        self._events: list[dict] = []
        self.dropped = 0
        self._pid = os.getpid()
        # fleet correlation keys (obs.fleet.set_fleet_identity) merged
        # into every recorded event's args — worker/rank/membership_epoch
        # labels that make multi-process traces joinable offline
        self._context: dict[str, Any] = {}

    @property
    def epoch_unix(self) -> float:
        """Wall-clock time at this tracer's epoch — the anchor the fleet
        merger uses for coarse cross-process clock alignment."""
        return self._epoch_unix

    def set_context(self, **kv: Any) -> None:
        """Replace the label set stamped into every subsequent event's
        args (explicit per-event args win on key collision)."""
        self._context = {k: v for k, v in kv.items() if v is not None}

    # ------------------------------------------------------------- clock
    def now(self) -> float:
        """Seconds on the tracer clock (pair with :meth:`add_span`)."""
        return self._clock()

    def _us(self, t: float) -> float:
        return (t - self._t0) * 1e6

    # ----------------------------------------------------------- record
    def _append(self, ev: dict) -> None:
        if not self.enabled:
            return
        if self._context:
            ev["args"] = {**self._context, **ev.get("args", {})}
        with self._lock:
            if len(self._events) >= self.capacity:
                self.dropped += 1
                return
            self._events.append(ev)

    @contextlib.contextmanager
    def span(self, name: str, **args: Any) -> Iterator[None]:
        """Record the enclosed block as one complete ("X") event."""
        if not self.enabled:
            yield
            return
        start = self._clock()
        try:
            yield
        except BaseException as e:
            args = {**args, "error": type(e).__name__}
            raise
        finally:
            end = self._clock()
            self._append({
                "name": name,
                "ph": "X",
                "ts": self._us(start),
                "dur": (end - start) * 1e6,
                "pid": self._pid,
                "tid": threading.get_ident() % 0x7FFFFFFF,
                **({"args": args} if args else {}),
            })

    def add_span(
        self, name: str, dur_s: float, end: float | None = None, **args: Any
    ) -> None:
        """Record a span of known duration ending at ``end`` (tracer-clock
        seconds, default now).  For intervals whose start was stamped on a
        DIFFERENT monotonic clock: only the duration crosses over, so no
        cross-clock timestamp arithmetic can skew the timeline."""
        if not self.enabled:
            return
        end = self._clock() if end is None else end
        dur_s = max(float(dur_s), 0.0)
        self._append({
            "name": name,
            "ph": "X",
            "ts": self._us(end - dur_s),
            "dur": dur_s * 1e6,
            "pid": self._pid,
            "tid": threading.get_ident() % 0x7FFFFFFF,
            **({"args": args} if args else {}),
        })

    _FLOW_PH = {"out": "s", "step": "t", "in": "f"}

    def flow(
        self,
        direction: str,
        flow_id: int,
        name: str = "wire",
        ts: float | None = None,
        **args: Any,
    ) -> None:
        """Record a Chrome-trace flow event (``ph`` s/t/f) — the arrow
        primitive that links spans causally ACROSS processes in the
        merged fleet trace.  ``direction`` is "out" (start), "step"
        (intermediate) or "in" (finish); events sharing ``flow_id`` (and
        the fixed "wire" category) form one arrow.  ``ts`` places the
        event (tracer-clock seconds, default now) — it must fall inside
        the span the arrow should bind to on this thread."""
        ph = self._FLOW_PH[direction]
        ev = {
            "name": name,
            "cat": "wire",
            "ph": ph,
            "id": int(flow_id),
            "ts": self._us(self._clock() if ts is None else ts),
            "pid": self._pid,
            "tid": threading.get_ident() % 0x7FFFFFFF,
            **({"args": args} if args else {}),
        }
        if ph == "f":
            ev["bp"] = "e"  # bind to the enclosing slice, not the next
        self._append(ev)

    def instant(self, name: str, **args: Any) -> None:
        self._append({
            "name": name,
            "ph": "i",
            "s": "t",
            "ts": self._us(self._clock()),
            "pid": self._pid,
            "tid": threading.get_ident() % 0x7FFFFFFF,
            **({"args": args} if args else {}),
        })

    # ------------------------------------------------------------ export
    def events(self) -> list[dict]:
        with self._lock:
            return list(self._events)

    def event_count(self) -> int:
        """How many events are recorded — pair with :meth:`events_since`
        for incremental readers (obs.perf digests only the spans of the
        round that just ended) without copying the whole ring each
        round."""
        with self._lock:
            return len(self._events)

    def events_since(self, start: int) -> list[dict]:
        """The events recorded at index ``start`` onward (a prior
        :meth:`event_count` reading)."""
        with self._lock:
            return list(self._events[start:])

    def to_chrome(self) -> dict:
        """Chrome trace event JSON object; events sorted by ``ts`` so the
        exported timeline is monotonic."""
        evs = sorted(self.events(), key=lambda e: e["ts"])
        return {
            "traceEvents": evs,
            "displayTimeUnit": "ms",
            "otherData": {
                "producer": "fedrec_tpu.obs",
                "epoch_unix": self._epoch_unix,
                "dropped_events": self.dropped,
            },
        }

    def save(self, path) -> dict:
        """Write the Perfetto/Chrome-trace JSON; returns what was written."""
        doc = self.to_chrome()
        with open(path, "w") as f:
            json.dump(doc, f)
        return doc

    def reset(self) -> None:
        with self._lock:
            self._events.clear()
            self.dropped = 0


# ------------------------------------------------------------- global default
_default_tracer = Tracer()
_default_lock = threading.Lock()


def get_tracer() -> Tracer:
    """The process-wide default tracer every subsystem records into."""
    return _default_tracer


def set_tracer(tracer: Tracer) -> Tracer:
    """Swap the default tracer (tests); returns the previous one."""
    global _default_tracer
    with _default_lock:
        prev = _default_tracer
        _default_tracer = tracer
        return prev
