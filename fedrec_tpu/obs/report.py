"""Run-report renderer: JSONL event log + registry snapshots + trace -> one page.

The artifact contract (written by the Trainer under ``obs.dir``, by
``fedrec-serve --obs-dir``, and by ``benchmarks/serve_load.py --obs-dir``):

* ``metrics.jsonl`` — interleaved JSON lines of two kinds:
  - metric-log records (``MetricLogger`` schema: ``{"step": ..,
    "elapsed_sec": .., "training_loss": .., ...}``), and
  - registry snapshots (``{"kind": "registry_snapshot", "ts": ..,
    "metrics": {...}}``, one per round / one at shutdown);
* ``trace.json`` — Chrome-trace/Perfetto host spans
  (:mod:`fedrec_tpu.obs.tracing`);
* ``prometheus.txt`` — final text exposition (scrape-equivalent).

``build_report`` digests those into one dict (round throughput, loss
trajectory, serve p50/p99, prefetch stalls, epsilon-spent trajectory,
cap-overflow counts, span summary) and ``render_text`` prints it — the
``fedrec-obs`` CLI is a thin wrapper over these two calls.
"""

from __future__ import annotations

import json
import os
from pathlib import Path
from typing import Any


def rotate_jsonl(path, max_mb: float) -> bool:
    """Size-based rotation for a JSONL event log: when ``path`` exceeds
    ``max_mb`` MB it is renamed to ``<path>.1`` (replacing any previous
    rotation — one level kept, so the log is bounded at ~2×max_mb) and the
    next append starts a fresh file.  ``max_mb <= 0`` disables.  Returns
    True when a rotation happened.  Writers call this BEFORE appending;
    :func:`load_jsonl` reads the rotated file first, so record order is
    preserved across the boundary."""
    if not max_mb or max_mb <= 0:
        return False
    p = Path(path)
    try:
        if p.stat().st_size < max_mb * 1e6:
            return False
        os.replace(p, Path(str(p) + ".1"))
        return True
    except OSError:
        return False


def load_jsonl(path) -> tuple[list[dict], list[dict]]:
    """Split a metrics JSONL event log into (metric_log_records, snapshots).
    Unparseable lines are skipped (a crashed writer may leave a torn tail).
    A rotated sibling (``<path>.1``, see :func:`rotate_jsonl`) is read
    FIRST so records come back in write order across the rotation."""
    records: list[dict] = []
    snapshots: list[dict] = []
    main = Path(path)
    rotated = Path(str(path) + ".1")
    paths = [p for p in (rotated, main) if p.exists()]
    if not paths:
        raise FileNotFoundError(f"no event log at {path}")
    for p in paths:
        with open(p) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    obj = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if not isinstance(obj, dict):
                    continue
                if obj.get("kind") == "registry_snapshot":
                    snapshots.append(obj)
                else:
                    records.append(obj)
    return records, snapshots


def load_trace(path) -> list[dict]:
    with open(path) as f:
        doc = json.load(f)
    events = doc["traceEvents"] if isinstance(doc, dict) else doc
    return [e for e in events if isinstance(e, dict)]


# ------------------------------------------------------- snapshot accessors
def _metric_values(snap: dict, name: str) -> list[dict]:
    m = snap.get("metrics", {}).get(name)
    return m.get("values", []) if m else []


def snapshot_value(snap: dict, name: str, labels: dict | None = None) -> float | None:
    """First matching counter/gauge cell value in a snapshot, else None."""
    for row in _metric_values(snap, name):
        if labels is None or row.get("labels") == labels:
            if "value" in row:
                return row["value"]
    return None


def snapshot_total(snap: dict, name: str) -> float | None:
    """Sum over ALL label cells of a counter/gauge (e.g. the per-bucket
    ``serve.batches_total``); None when the metric has no cells."""
    values = [row["value"] for row in _metric_values(snap, name) if "value" in row]
    return sum(values) if values else None


def snapshot_histogram(snap: dict, name: str) -> dict | None:
    for row in _metric_values(snap, name):
        if "buckets" in row:
            return row
    return None


def histogram_quantile(row: dict, q: float) -> float | None:
    """Quantile from an exported snapshot histogram row — parses the
    ``{"le": count}`` dict into (bounds, counts) and delegates to
    :func:`fedrec_tpu.obs.registry.quantile_from_counts`, the ONE
    estimator ``Histogram.quantile`` also uses."""
    from fedrec_tpu.obs.registry import quantile_from_counts

    buckets = row.get("buckets", {})
    if not buckets or not row.get("count"):
        return None
    bounds: list[float] = []
    counts: list[int] = []
    inf_count = 0
    for le, n in buckets.items():
        if le == "+Inf":
            inf_count = n
        else:
            bounds.append(float(le))
            counts.append(n)
    order = sorted(range(len(bounds)), key=lambda i: bounds[i])
    bounds = [bounds[i] for i in order]
    counts = [counts[i] for i in order]
    counts.append(inf_count)
    return quantile_from_counts(q, bounds, counts)


def quantile_is_lower_bound(row: dict, q: float) -> bool:
    """True when the q-rank of an exported histogram row falls in the +Inf
    overflow bucket — :func:`histogram_quantile` then clamps to the last
    finite bucket edge, so the estimate is a LOWER BOUND, not a value
    (e.g. every observation above the largest bucket).  The report
    annotates such estimates with ``>=``."""
    buckets = row.get("buckets", {})
    total = sum(buckets.values())
    if total == 0:
        return False
    inf_count = buckets.get("+Inf", 0)
    return q * total > total - inf_count


def quality_detail_from_snapshot(snap: dict) -> dict:
    """The full quality digest out of one registry snapshot — every slice's
    metric quartet + impression count, the reliability table, score
    separation, and the serving drift verdict.  The ``fedrec-obs quality``
    verb renders this; ``build_report``'s Quality section is the compact
    subset."""
    detail: dict[str, Any] = {}
    slices: dict[str, dict] = {}
    for metric in ("auc", "mrr", "ndcg5", "ndcg10"):
        for row in _metric_values(snap, f"eval.{metric}"):
            if "value" in row:
                slices.setdefault(
                    row["labels"].get("slice", "?"), {}
                )[metric] = row["value"]
    for row in _metric_values(snap, "eval.slice_impressions"):
        if "value" in row:
            name = row["labels"].get("slice", "?")
            if name in slices:
                slices[name]["count"] = row["value"]
    if slices:
        detail["slices"] = dict(sorted(slices.items()))
    cal: dict[int, dict] = {}
    for key, name in (
        ("count", "eval.calibration_count"),
        ("confidence", "eval.calibration_confidence"),
        ("accuracy", "eval.calibration_accuracy"),
    ):
        for row in _metric_values(snap, name):
            if "value" in row:
                cal.setdefault(int(row["labels"].get("bin", -1)), {})[key] = (
                    row["value"]
                )
    if cal:
        detail["calibration"] = [
            {"bin": b, **cal[b]} for b in sorted(cal)
        ]
    for key, name in (
        ("ece", "eval.ece"),
        ("score_separation", "eval.score_separation"),
        ("score_dprime", "eval.score_dprime"),
        ("slices_skipped", "eval.slices_skipped_total"),
        ("quality_outlier_client_evals", "eval.quality_outlier_clients_total"),
    ):
        v = snapshot_value(snap, name)
        if v is not None:
            detail[key] = v
    clients = {
        row["labels"].get("client", "?"): row["value"]
        for row in _metric_values(snap, "eval.client_auc")
        if "value" in row
    }
    if clients:
        detail["client_auc"] = dict(sorted(clients.items()))
    drift = {
        key: v
        for key, name in (
            ("score_shift_mean", "serve.drift_score_shift_mean"),
            ("score_shift_max", "serve.drift_score_shift_max"),
            ("topk_jaccard", "serve.drift_topk_jaccard"),
            ("rank_churn", "serve.drift_rank_churn"),
            ("checks", "serve.drift_checks_total"),
        )
        if (v := snapshot_value(snap, name)) is not None
    }
    if drift:
        detail["drift"] = drift
    return detail


def perf_detail_from_snapshot(snap: dict) -> dict:
    """The performance digest out of one registry snapshot — the live
    efficiency gauges (obs.perf), the per-round roofline verdict counts,
    the HBM component attribution and the compile-cost table.  The
    ``fedrec-obs perf`` verb renders this; ``build_report``'s Perf
    section and the fleet report's per-worker perf columns are compact
    subsets of the SAME extraction."""
    detail: dict[str, Any] = {}
    for key, name in (
        ("samples_per_sec", "perf.samples_per_sec"),
        ("mfu", "perf.mfu"),
        ("hbm_fraction", "perf.hbm_fraction"),
        ("step_flops", "perf.step_flops"),
        ("host_ms_per_step", "perf.host_ms_per_step"),
        ("dispatch_ms_per_step", "perf.dispatch_ms_per_step"),
        ("captures", "perf.captures_total"),
        ("capture_failures", "perf.capture_failures_total"),
    ):
        v = (
            snapshot_total(snap, name)
            if name == "perf.captures_total"  # labeled per reason: sum
            else snapshot_value(snap, name)
        )
        if v is not None:
            detail[key] = v
    verdicts = {
        row["labels"].get("verdict", "?"): row["value"]
        for row in _metric_values(snap, "perf.roofline_rounds_total")
        if "value" in row and row["value"] > 0
    }
    if verdicts:
        detail["verdict_rounds"] = verdicts
        detail["verdict"] = max(verdicts, key=verdicts.get)
    hbm = {
        row["labels"].get("component", "?"): row["value"]
        for row in _metric_values(snap, "hbm.component_bytes")
        if "value" in row
    }
    if hbm:
        detail["hbm_components"] = hbm
    cost: dict[str, dict] = {}
    for key, name in (
        ("flops", "xla.cost_flops"),
        ("bytes_accessed", "xla.cost_bytes_accessed"),
        ("arithmetic_intensity", "xla.cost_arithmetic_intensity"),
    ):
        for row in _metric_values(snap, name):
            if "value" in row:
                cost.setdefault(row["labels"].get("fn", "?"), {})[key] = (
                    row["value"]
                )
    if cost:
        detail["compile_cost"] = dict(sorted(cost.items()))
    return detail


def span_summary(
    trace_events: list[dict], names: set | tuple | None = None
) -> dict[str, dict]:
    """Per-span-name `{count, total_ms, mean_ms, max_ms}` rollup over
    Chrome-trace complete ("X") events — THE aggregation behind
    ``build_report``'s span table and ``fedrec-obs perf``'s phase table
    (one definition, so the two views cannot drift on the same trace).
    ``names`` filters to a span subset (e.g. the round phases)."""
    spans: dict[str, dict] = {}
    for e in trace_events:
        if e.get("ph") != "X":
            continue
        name = e.get("name")
        if names is not None and name not in names:
            continue
        s = spans.setdefault(
            name, {"count": 0, "total_ms": 0.0, "max_ms": 0.0}
        )
        dur_ms = float(e.get("dur", 0.0)) / 1e3
        s["count"] += 1
        s["total_ms"] += dur_ms
        s["max_ms"] = max(s["max_ms"], dur_ms)
    for s in spans.values():
        s["total_ms"] = round(s["total_ms"], 3)
        s["max_ms"] = round(s["max_ms"], 3)
        s["mean_ms"] = round(s["total_ms"] / s["count"], 3) if s["count"] else 0.0
    return spans


# -------------------------------------------------------------- the report
def build_report(
    records: list[dict],
    snapshots: list[dict],
    trace_events: list[dict] | None = None,
) -> dict:
    report: dict[str, Any] = {}

    # ---- training rounds (MetricLogger schema: round + training_loss)
    rounds = [r for r in records if "round" in r and "training_loss" in r]
    if rounds:
        first, last = rounds[0], rounds[-1]
        elapsed = float(last.get("elapsed_sec", 0)) - float(first.get("elapsed_sec", 0))
        tr: dict[str, Any] = {
            "rounds": len(rounds),
            "first_loss": first["training_loss"],
            "last_loss": last["training_loss"],
        }
        if len(rounds) > 1 and elapsed > 0:
            tr["rounds_per_sec"] = round((len(rounds) - 1) / elapsed, 4)
        # unified key scheme (val_auc/val_mrr/val_ndcg5/val_ndcg10) with a
        # legacy fallback so pre-rename artifacts (valid_auc/val_ndcg@5)
        # still render
        _EVAL_KEYS = (
            ("val_auc", "valid_auc"),
            ("val_mrr", "valid_mrr"),
            ("val_ndcg5", "val_ndcg@5"),
            ("val_ndcg10", "val_ndcg@10"),
        )
        evals = [r for r in rounds if "val_auc" in r or "valid_auc" in r]
        if evals:
            last_ev = evals[-1]
            tr["last_eval"] = {
                new: (last_ev[new] if new in last_ev else last_ev[old])
                for new, old in _EVAL_KEYS
                if new in last_ev or old in last_ev
            }
        report["training"] = tr

    # ---- epsilon trajectory (per-round records and/or snapshots)
    def _round_key(r: dict):
        k = r.get("round", r.get("step"))
        # MetricLogger float-coerces numerics; a round index reads better whole
        return int(k) if isinstance(k, float) and k.is_integer() else k

    eps = [
        (_round_key(r), r["privacy.epsilon_spent"])
        for r in records
        if "privacy.epsilon_spent" in r
    ]
    if not eps:
        eps = [
            (i, v)
            for i, s in enumerate(snapshots)
            if (v := snapshot_value(s, "privacy.epsilon_spent")) is not None
        ]
    if eps:
        report["privacy"] = {
            "epsilon_trajectory": eps,
            "epsilon_spent": eps[-1][1],
        }

    last = snapshots[-1] if snapshots else None
    if last is not None:
        # ---- serving latency: prefer the collector gauges, fall back to
        # the histogram estimate
        p50 = snapshot_value(last, "serve.p50_ms")
        p99 = snapshot_value(last, "serve.p99_ms")
        hist = snapshot_histogram(last, "serve.latency_ms")
        serve: dict[str, Any] = {}
        if p50 is None and hist is not None:
            p50 = histogram_quantile(hist, 0.50)
            p99 = histogram_quantile(hist, 0.99)
            # all-mass-in-overflow (or a tail past the largest bucket):
            # the estimator clamps to the last finite edge — an honest
            # LOWER BOUND the report must say so about, not a value
            if p50 is not None and quantile_is_lower_bound(hist, 0.50):
                serve["p50_is_lower_bound"] = True
            if p99 is not None and quantile_is_lower_bound(hist, 0.99):
                serve["p99_is_lower_bound"] = True
        if p50 is not None:
            serve["p50_ms"] = round(p50, 3)
        if p99 is not None:
            serve["p99_ms"] = round(p99, 3)
        for key, name in (
            ("served", "serve.requests_total"),
            ("rejected", "serve.rejected_total"),
            ("deadline_missed", "serve.deadline_missed_total"),
            ("batches", "serve.batches_total"),  # labeled per bucket: sum
            ("queue_depth", "serve.queue_depth"),
            ("generation", "serve.generation"),
        ):
            v = snapshot_total(last, name) if key == "batches" \
                else snapshot_value(last, name)
            if v is not None:
                serve[key] = v
        if serve:
            report["serving"] = serve

        # ---- prefetch health
        pf = {
            key: v
            for key, name in (
                ("queue_depth", "data.prefetch.queue_depth"),
                ("producer_stalls", "data.prefetch.producer_stall_total"),
                ("consumer_stalls", "data.prefetch.consumer_stall_total"),
                ("items", "data.prefetch.items_total"),
            )
            if (v := snapshot_value(last, name)) is not None
        }
        if pf:
            report["prefetch"] = pf

        # ---- training health (numeric sentry + device watchdogs)
        health = {
            key: v
            for key, name, total in (
                ("nonfinite_steps", "health.nonfinite_steps_total", False),
                ("outlier_client_rounds", "health.outlier_clients_total", False),
                ("param_norm", "health.param_norm", False),
                ("clip_rate_last", "privacy.clip_rate_last", False),
                ("xla_compiles", "xla.compiles_total", True),       # per-fn: sum
                ("xla_recompiles", "xla.recompiles_total", True),
                ("recompile_storms", "xla.recompile_storms_total", False),
            )
            if (
                v := (snapshot_total(last, name) if total
                      else snapshot_value(last, name))
            ) is not None
        }
        if health:
            report["health"] = health

        # ---- robustness: chaos faults, quarantines/rollbacks, the
        # robust-aggregation method in use (label of the per-round counter)
        rb: dict[str, Any] = {}
        faults = {
            row["labels"].get("kind", "?"): row["value"]
            for row in _metric_values(last, "chaos.faults_total")
            if "value" in row
        }
        if faults:
            rb["faults_injected"] = faults
        for key, name in (
            ("quarantines", "fed.quarantines_total"),
            ("rollbacks", "fed.rollbacks_total"),
            ("quarantine_active", "fed.quarantine_active"),
        ):
            v = snapshot_value(last, name)
            if v:
                rb[key] = v
        methods = {
            row["labels"].get("method", "?"): row["value"]
            for row in _metric_values(last, "fed.robust_rounds_total")
            if "value" in row
        }
        if methods:
            rb["robust_method"] = max(methods, key=methods.get)
            rb["robust_rounds"] = sum(methods.values())
        if rb:
            report["robustness"] = rb

        # ---- participation: the cohort engine's view of the round —
        # population size, last cohort draw/report counts, cumulative
        # dropout/deadline/quorum events, slot churn, coverage. Keyed on
        # fed.population_clients > 0 so a cross-silo run stays silent.
        pop_size = snapshot_value(last, "fed.population_clients")
        if pop_size:
            part: dict[str, Any] = {"population": pop_size}
            for key, name in (
                ("cohort_sampled", "fed.cohort_sampled"),
                ("cohort_reporting", "fed.cohort_reporting"),
                ("dropouts", "fed.pop_dropouts_total"),
                ("deadline_cuts", "fed.deadline_cuts_total"),
                ("quorum_replays", "fed.quorum_replays_total"),
                ("slot_swaps", "fed.cohort_slot_swaps_total"),
                ("coverage", "fed.population_coverage"),
            ):
                v = snapshot_value(last, name)
                if v is not None:
                    part[key] = v
            report["participation"] = part

        # ---- communication: measured wire traffic — per-path byte
        # counters ("cohort" = the simulated in-graph client uplink,
        # counted only under an active codec; "dcn" = the coordinator's
        # real cross-host gather, counted in EVERY mode, dense bytes
        # included) plus the per-client compression ratio. Keyed on any
        # up-bytes having been counted: single-process runs without a
        # codec stay silent, a multi-process run always shows its DCN
        # bytes.
        up_by_path = {
            row["labels"].get("path", "?"): row["value"]
            for row in _metric_values(last, "fed.dcn_bytes_up_total")
            if "value" in row and row["value"] > 0
        }
        if up_by_path:
            comm: dict[str, Any] = {
                "bytes_up": up_by_path,
                "bytes_up_total": sum(up_by_path.values()),
            }
            down_by_path = {
                row["labels"].get("path", "?"): row["value"]
                for row in _metric_values(last, "fed.dcn_bytes_down_total")
                if "value" in row and row["value"] > 0
            }
            if down_by_path:
                comm["bytes_down"] = down_by_path
                comm["bytes_down_total"] = sum(down_by_path.values())
            ratio = snapshot_value(last, "fed.dcn_compression_ratio")
            if ratio:
                comm["compression_ratio"] = ratio
            else:
                # explicit "codec: none": this artifact moved DENSE
                # traffic — absent-because-uncompressed, not
                # absent-because-unmeasured (operators diffing two
                # reports must see which side ran a codec)
                comm["codec"] = "none"
            per_leaf = {
                row["labels"].get("leaf", "?"): row["value"]
                for row in _metric_values(
                    last, "fed.dcn_compression_ratio_leaf"
                )
                if "value" in row
            }
            if per_leaf:
                comm["compression_ratio_by_leaf"] = per_leaf
            srmse = snapshot_value(last, "fed.dcn_sketch_rmse")
            if srmse is not None:
                comm["sketch_rmse"] = srmse
            auto_map = next(
                (
                    r["dcn_auto_map_pinned"]
                    for r in reversed(records)
                    if "dcn_auto_map_pinned" in r
                ),
                None,
            )
            if isinstance(auto_map, str):
                # the trainer logs the map as a JSON string (the metric
                # logger stringifies anything non-numeric)
                try:
                    auto_map = json.loads(auto_map)
                except json.JSONDecodeError:
                    auto_map = None
            if isinstance(auto_map, dict) and auto_map:
                comm["auto_codec_map"] = auto_map
            misses = snapshot_value(last, "fed.dcn_deadline_misses_total")
            if misses:
                comm["deadline_misses"] = misses
            report["communication"] = comm

        # ---- sharding: the layout summary — fsdp shards + at-rest state
        # bytes per device, sharded-catalog occupancy/residency, and the
        # modeled owner-bucketed all_to_all traffic. Keyed on the layout
        # actually being sharded (fsdp > 1 or a per-step a2a wire model),
        # so a replicated run stays silent.
        fsdp = snapshot_value(last, "shard.fsdp_shards")
        a2a = snapshot_value(last, "shard.a2a_bytes_total")
        if (fsdp and fsdp > 1) or a2a:
            sh: dict[str, Any] = {}
            if fsdp:
                sh["fsdp_shards"] = fsdp
            for key, name in (
                ("state_bytes_per_device", "shard.state_bytes_per_device"),
                ("table_rows_per_device", "shard.table_rows_per_device"),
                ("table_occupancy", "shard.table_occupancy"),
                ("remote_gather_rows", "shard.remote_gather_rows"),
                ("a2a_bytes", "shard.a2a_bytes_total"),
            ):
                v = snapshot_value(last, name)
                if v is not None:
                    sh[key] = v
            report["sharding"] = sh

        # ---- membership: the elastic epoch layer's view — this worker's
        # epoch/world seat, the service-mirrored shrink/rejoin/lease-miss
        # totals, its own reform departures and heartbeat failures, and
        # the reshard cost of the last epoch hand-off. Keyed on the epoch
        # gauge existing: a fixed-world run stays silent.
        epoch = snapshot_value(last, "fed.membership_epoch")
        if epoch is not None:
            mem: dict[str, Any] = {"epoch": epoch}
            # shrinks/rejoins/lease_misses: the service's OWN counters
            # (its obs trio, PR-13) — the `_total` names; the legacy
            # pre-PR-13 worker-mirrored gauge names still render from
            # old artifacts
            for key, names in (
                ("world", ("fed.membership_world",)),
                ("shrinks", ("fed.membership_shrinks_total",
                             "fed.membership_shrinks")),
                ("rejoins", ("fed.membership_rejoins_total",
                             "fed.membership_rejoins")),
                ("lease_misses", ("fed.membership_lease_misses_total",
                                  "fed.membership_lease_misses")),
                ("heartbeat_failures", ("fed.lease_heartbeat_failures",)),
                ("reforms", ("fed.membership_reforms_total",)),
                ("reshard_seconds", ("shard.reshard_seconds",)),
                ("rows_recovered", ("shard.reshard_rows_recovered_total",)),
            ):
                for name in names:
                    v = snapshot_value(last, name)
                    if v is not None:
                        mem[key] = v
                        break
            report["membership"] = mem

        # ---- quality: sliced eval telemetry + calibration + serving
        # drift (obs.quality) — the compact subset of ONE extraction
        # (quality_detail_from_snapshot, shared with `fedrec-obs quality`
        # and the fleet report), so the three views can never disagree;
        # silent (empty detail) on a quality-off run
        detail = quality_detail_from_snapshot(last)
        if detail:
            ql: dict[str, Any] = {}
            slices_d = {
                name: m for name, m in detail.get("slices", {}).items()
                if "auc" in m
            }
            if slices_d:
                ql["slices"] = {
                    name: {
                        "auc": m["auc"],
                        **({"count": m["count"]} if "count" in m else {}),
                    }
                    for name, m in slices_d.items()
                }
                if "all" in slices_d:
                    ql["corpus_auc"] = slices_d["all"]["auc"]
                named = {
                    k: m["auc"] for k, m in slices_d.items() if k != "all"
                }
                if named:
                    ql["worst_slice"] = min(named, key=named.get)
                    ql["best_slice"] = max(named, key=named.get)
            for key in (
                "ece", "score_separation", "score_dprime",
                "quality_outlier_client_evals", "slices_skipped",
            ):
                if key in detail:
                    ql[key] = detail[key]
            if "drift" in detail:
                ql["drift"] = detail["drift"]
            report["quality"] = ql

        # ---- perf: the live efficiency gauges (obs.perf) — throughput,
        # MFU, the roofline-verdict round counts, HBM attribution and
        # compile cost, compacted from ONE extraction
        # (perf_detail_from_snapshot, shared with `fedrec-obs perf` and
        # the fleet report); silent on a perf-off run
        pdetail = perf_detail_from_snapshot(last)
        if pdetail:
            pf_sec: dict[str, Any] = {}
            for key in (
                "samples_per_sec", "mfu", "hbm_fraction",
                "host_ms_per_step", "dispatch_ms_per_step",
                "verdict", "verdict_rounds", "captures",
            ):
                if key in pdetail:
                    pf_sec[key] = pdetail[key]
            if "hbm_components" in pdetail:
                comps = {
                    k: v for k, v in pdetail["hbm_components"].items() if v
                }
                if comps:
                    pf_sec["hbm_top"] = max(comps, key=comps.get)
                    pf_sec["hbm_components"] = comps
            if "compile_cost" in pdetail:
                pf_sec["compiled_fns"] = len(pdetail["compile_cost"])
            if pf_sec:
                report["perf"] = pf_sec

        # ---- cap overflows
        overflow = snapshot_value(last, "train.cap_overflow_total")
        if overflow is not None:
            report["cap_overflow_steps"] = overflow

    # ---- alert lifecycle (the watch layer's {"kind":"alert"} records;
    # silent on a run with obs.slo.enabled=false — no records, no panel)
    from fedrec_tpu.obs.watch import active_alerts, alert_records

    alerts = alert_records(records)
    if alerts:
        by_event: dict[str, int] = {}
        for r in alerts:
            ev = str(r.get("event", "?"))
            by_event[ev] = by_event.get(ev, 0) + 1
        report["alerts"] = {
            "transitions": len(alerts),
            "by_event": by_event,
            "active": active_alerts(alerts),
            "recent": alerts[-8:],
        }

    # ---- span summary
    if trace_events:
        report["spans"] = dict(sorted(span_summary(trace_events).items()))

    return report


def render_text(report: dict) -> str:
    """Human-readable run report (the ``fedrec-obs report`` output)."""
    lines: list[str] = ["# fedrec_tpu run report", ""]
    tr = report.get("training")
    if tr:
        lines.append("## Training")
        lines.append(f"rounds: {tr['rounds']}")
        if "rounds_per_sec" in tr:
            lines.append(f"round throughput: {tr['rounds_per_sec']} rounds/s")
        lines.append(f"loss: {tr['first_loss']:.4f} -> {tr['last_loss']:.4f}")
        if "last_eval" in tr:
            ev = ", ".join(f"{k}={v:.4f}" for k, v in tr["last_eval"].items())
            lines.append(f"last eval: {ev}")
        lines.append("")
    pv = report.get("privacy")
    if pv:
        lines.append("## Privacy")
        lines.append(f"privacy.epsilon_spent: {pv['epsilon_spent']:.4f}")
        traj = ", ".join(f"r{r}={e:.3f}" for r, e in pv["epsilon_trajectory"][-8:])
        lines.append(f"trajectory (last 8): {traj}")
        lines.append("")
    sv = report.get("serving")
    if sv:
        lines.append("## Serving")
        if "p50_ms" in sv or "p99_ms" in sv:
            p50_pfx = ">=" if sv.get("p50_is_lower_bound") else ""
            p99_pfx = ">=" if sv.get("p99_is_lower_bound") else ""
            lines.append(
                f"latency: p50={p50_pfx}{sv.get('p50_ms')}ms "
                f"p99={p99_pfx}{sv.get('p99_ms')}ms"
            )
        counters = ", ".join(
            f"{k}={int(sv[k])}"
            for k in ("served", "rejected", "deadline_missed", "batches")
            if k in sv
        )
        if counters:
            lines.append(counters)
        if "queue_depth" in sv:
            lines.append(f"queue depth: {int(sv['queue_depth'])}")
        lines.append("")
    pf = report.get("prefetch")
    if pf:
        lines.append("## Prefetch")
        lines.append(
            "queue depth: "
            f"{int(pf.get('queue_depth', 0))}, producer stalls: "
            f"{int(pf.get('producer_stalls', 0))}, consumer stalls: "
            f"{int(pf.get('consumer_stalls', 0))}"
        )
        lines.append("")
    hl = report.get("health")
    if hl:
        lines.append("## Health")
        if "nonfinite_steps" in hl:
            lines.append(f"non-finite step cells: {int(hl['nonfinite_steps'])}")
        if "outlier_client_rounds" in hl:
            lines.append(
                f"outlier client-rounds: {int(hl['outlier_client_rounds'])}"
            )
        if "param_norm" in hl:
            lines.append(f"param norm (last): {hl['param_norm']:.4g}")
        if "clip_rate_last" in hl:
            lines.append(f"dp clip rate (last step): {hl['clip_rate_last']:.4f}")
        if "xla_compiles" in hl:
            lines.append(
                f"xla compiles: {int(hl['xla_compiles'])} "
                f"(recompiles: {int(hl.get('xla_recompiles', 0))}, "
                f"storms: {int(hl.get('recompile_storms', 0))})"
            )
        lines.append("")
    al = report.get("alerts")
    if al:
        lines.append("## Alerts")
        by = ", ".join(
            f"{k}={v}" for k, v in sorted(al["by_event"].items())
        )
        lines.append(f"transitions: {al['transitions']} ({by})")
        if al["active"]:
            lines.append(f"STILL FIRING ({len(al['active'])}):")
            for r in al["active"]:
                lines.append(
                    f"  [{r.get('severity', '?')}] {r.get('key', '?')}: "
                    f"{r.get('summary', '')}"
                )
        else:
            lines.append("active: none (every fired alert resolved)")
        lines.append("")
    rb = report.get("robustness")
    if rb:
        lines.append("## Robustness")
        if "robust_method" in rb:
            lines.append(
                f"aggregation: {rb['robust_method']} "
                f"({int(rb.get('robust_rounds', 0))} rounds)"
            )
        if "faults_injected" in rb:
            lines.append(
                "faults injected: "
                + ", ".join(
                    f"{k}={int(v)}"
                    for k, v in sorted(rb["faults_injected"].items())
                )
            )
        if "quarantines" in rb or "rollbacks" in rb:
            lines.append(
                f"clients quarantined: {int(rb.get('quarantines', 0))}, "
                f"rollbacks: {int(rb.get('rollbacks', 0))}, "
                f"active: {int(rb.get('quarantine_active', 0))}"
            )
        lines.append("")
    part = report.get("participation")
    if part:
        lines.append("## Participation")
        lines.append(
            f"logical clients: {int(part['population'])}"
            + (
                f", coverage: {part['coverage']:.1%}"
                if "coverage" in part else ""
            )
        )
        if "cohort_sampled" in part or "cohort_reporting" in part:
            lines.append(
                f"last round: sampled={int(part.get('cohort_sampled', 0))} "
                f"reporting={int(part.get('cohort_reporting', 0))}"
            )
        lines.append(
            f"dropouts: {int(part.get('dropouts', 0))}, "
            f"deadline cuts: {int(part.get('deadline_cuts', 0))}, "
            f"quorum replays: {int(part.get('quorum_replays', 0))}, "
            f"slot swaps: {int(part.get('slot_swaps', 0))}"
        )
        lines.append("")
    comm = report.get("communication")
    if comm:
        lines.append("## Communication")

        def _mb(n: float) -> str:
            return f"{n / (1024 * 1024):.2f} MB"

        up = ", ".join(
            f"{p}={_mb(v)}" for p, v in sorted(comm["bytes_up"].items())
        )
        lines.append(f"client->server bytes: {up}")
        if "bytes_down" in comm:
            down = ", ".join(
                f"{p}={_mb(v)}" for p, v in sorted(comm["bytes_down"].items())
            )
            lines.append(f"server->client bytes: {down} (full precision)")
        if "compression_ratio" in comm:
            lines.append(
                f"update compression: {comm['compression_ratio']:.1f}x "
                "(dense/encoded, per client-round payload)"
            )
        else:
            lines.append("codec: none (dense payloads — no compression ran)")
        if "compression_ratio_by_leaf" in comm:
            cells = ", ".join(
                f"{leaf}={v:.1f}x"
                for leaf, v in sorted(
                    comm["compression_ratio_by_leaf"].items()
                )
            )
            lines.append(f"per-layer compression: {cells}")
        if "sketch_rmse" in comm:
            lines.append(
                f"sketch reconstruction rmse: {comm['sketch_rmse']:.3e} "
                "(own decoded contribution vs dense, pooled)"
            )
        if "auto_codec_map" in comm:
            picks = ", ".join(
                f"{leaf}:{c}"
                for leaf, c in sorted(comm["auto_codec_map"].items())
            )
            lines.append(f"auto codec map (pinned): {picks}")
        if "deadline_misses" in comm:
            lines.append(f"dcn deadline misses: {int(comm['deadline_misses'])}")
        lines.append("")
    shd = report.get("sharding")
    if shd:
        lines.append("## Sharding")

        def _mib(n: float) -> str:
            return f"{n / (1024 * 1024):.2f} MB"

        layout = []
        if shd.get("fsdp_shards"):
            layout.append(f"fsdp shards: {int(shd['fsdp_shards'])}")
        if "state_bytes_per_device" in shd:
            layout.append(
                f"state/device: {_mib(shd['state_bytes_per_device'])}"
            )
        if layout:
            lines.append(", ".join(layout))
        if "table_rows_per_device" in shd:
            occ = (
                f", occupancy: {shd['table_occupancy']:.1%}"
                if "table_occupancy" in shd else ""
            )
            lines.append(
                f"catalog rows/device: {int(shd['table_rows_per_device'])}"
                + occ
            )
        if "a2a_bytes" in shd:
            remote = (
                f" (worst-case {int(shd['remote_gather_rows'])} remote "
                "rows/step)"
                if "remote_gather_rows" in shd else ""
            )
            lines.append(
                f"gather all_to_all: {_mib(shd['a2a_bytes'])}{remote}"
            )
        lines.append("")
    mem = report.get("membership")
    if mem:
        lines.append("## Membership")
        lines.append(
            f"epoch: {int(mem['epoch'])}"
            + (f", world: {int(mem['world'])}" if "world" in mem else "")
        )
        lines.append(
            f"shrinks: {int(mem.get('shrinks', 0))}, "
            f"rejoins: {int(mem.get('rejoins', 0))}, "
            f"reform departures (this worker): {int(mem.get('reforms', 0))}"
        )
        lines.append(
            f"lease misses: {int(mem.get('lease_misses', 0))}, "
            f"heartbeat failures: {int(mem.get('heartbeat_failures', 0))}"
        )
        if "reshard_seconds" in mem:
            rows = (
                f", rows recovered: {int(mem['rows_recovered'])}"
                if "rows_recovered" in mem else ""
            )
            lines.append(
                f"last epoch hand-off: {mem['reshard_seconds']:.3f}s{rows}"
            )
        lines.append("")
    ql = report.get("quality")
    if ql:
        lines.append("## Quality")
        slices = ql.get("slices", {})
        if "corpus_auc" in ql:
            n = slices.get("all", {}).get("count")
            over = f" over {int(n)} impressions" if n is not None else ""
            lines.append(f"corpus auc: {ql['corpus_auc']:.4f}{over}")
        if "worst_slice" in ql:
            w, b = ql["worst_slice"], ql["best_slice"]
            n_named = len(slices) - (1 if "all" in slices else 0)
            lines.append(
                f"slices: {n_named} — worst {w} "
                f"auc={slices[w]['auc']:.4f}, best {b} "
                f"auc={slices[b]['auc']:.4f}"
            )
        if "slices_skipped" in ql and ql["slices_skipped"]:
            lines.append(
                f"slices skipped (empty/degenerate): "
                f"{int(ql['slices_skipped'])}"
            )
        if "ece" in ql:
            lines.append(f"calibration: ece={ql['ece']:.4f}")
        if "score_separation" in ql:
            dp = (
                f" (d'={ql['score_dprime']:.3f})"
                if "score_dprime" in ql else ""
            )
            lines.append(
                f"score separation: {ql['score_separation']:.4f}{dp}"
            )
        if "quality_outlier_client_evals" in ql:
            lines.append(
                "quality-outlier client-evals: "
                f"{int(ql['quality_outlier_client_evals'])}"
            )
        dr = ql.get("drift")
        if dr:
            parts = []
            if "score_shift_mean" in dr:
                parts.append(
                    f"|Δscore| mean={dr['score_shift_mean']:.4g} "
                    f"max={dr.get('score_shift_max', 0):.4g}"
                )
            if "topk_jaccard" in dr:
                parts.append(
                    f"top-k jaccard={dr['topk_jaccard']:.3f} "
                    f"(churn {dr.get('rank_churn', 0):.3f})"
                )
            lines.append(
                f"serving drift (last swap, {int(dr.get('checks', 0))} "
                f"probe check(s)): " + ", ".join(parts)
            )
        lines.append("")
    pfm = report.get("perf")
    if pfm:
        lines.append("## Perf")
        head = []
        if "samples_per_sec" in pfm:
            head.append(f"throughput: {pfm['samples_per_sec']:.1f} samples/s")
        if "mfu" in pfm:
            head.append(f"mfu: {pfm['mfu']:.4f}")
        if "hbm_fraction" in pfm:
            head.append(f"hbm: {pfm['hbm_fraction']:.3f} of peak")
        if head:
            lines.append(", ".join(head) + " (last round)")
        if "host_ms_per_step" in pfm or "dispatch_ms_per_step" in pfm:
            lines.append(
                f"per step: host {pfm.get('host_ms_per_step', 0):.2f} ms, "
                f"dispatch {pfm.get('dispatch_ms_per_step', 0):.2f} ms"
            )
        if "verdict_rounds" in pfm:
            counts = ", ".join(
                f"{k}={int(v)}"
                for k, v in sorted(pfm["verdict_rounds"].items())
            )
            lines.append(f"roofline verdicts (rounds): {counts}")
        if "hbm_components" in pfm:
            def _cmb(n: float) -> str:
                return f"{n / (1024 * 1024):.1f} MB"

            comps = ", ".join(
                f"{k}={_cmb(v)}"
                for k, v in sorted(
                    pfm["hbm_components"].items(),
                    key=lambda kv: -kv[1],
                )
            )
            lines.append(f"hbm by component: {comps}")
        if "captures" in pfm and pfm["captures"]:
            lines.append(
                f"capture windows: {int(pfm['captures'])} "
                "(see perf_capture_* under the obs dir)"
            )
        if "compiled_fns" in pfm:
            lines.append(
                f"compile-cost rows: {int(pfm['compiled_fns'])} "
                "(fedrec-obs perf for the table)"
            )
        lines.append("")
    if "cap_overflow_steps" in report:
        lines.append(f"cap-overflow steps: {int(report['cap_overflow_steps'])}")
        lines.append("")
    spans = report.get("spans")
    if spans:
        lines.append("## Host spans")
        lines.append(f"{'name':<24} {'count':>7} {'total_ms':>10} {'mean_ms':>9} {'max_ms':>9}")
        for name, s in spans.items():
            lines.append(
                f"{name:<24} {s['count']:>7} {s['total_ms']:>10} "
                f"{s['mean_ms']:>9} {s['max_ms']:>9}"
            )
        lines.append("")
    if len(lines) == 2:
        lines.append("(no recognizable records — is this a fedrec obs artifact?)")
    return "\n".join(lines)


def dump_artifacts(
    obs_dir, registry=None, tracer=None, trace_tag: str | None = None
) -> dict[str, str]:
    """Write the run's observability artifacts into ``obs_dir``:
    ``metrics.jsonl`` (append one final registry snapshot), ``trace.json``
    (Perfetto host spans), ``prometheus.txt`` (text exposition).  Shared
    shutdown path for the Trainer, ``fedrec-serve`` and ``serve_load``.

    ``trace_tag`` (elastic workers pass their membership epoch, e.g.
    ``"e2"``) ADDITIONALLY writes the trace as ``trace_<tag>.json`` —
    each incarnation's span history survives the respawn that would
    otherwise overwrite ``trace.json``, and ``fedrec-obs fleet-trace``
    merges every incarnation into the fleet timeline."""
    from fedrec_tpu.obs.registry import get_registry
    from fedrec_tpu.obs.tracing import get_tracer

    registry = registry or get_registry()
    tracer = tracer or get_tracer()
    out_dir = Path(obs_dir)
    out_dir.mkdir(parents=True, exist_ok=True)
    paths = {
        "metrics": str(out_dir / "metrics.jsonl"),
        "trace": str(out_dir / "trace.json"),
        "prometheus": str(out_dir / "prometheus.txt"),
    }
    registry.write_snapshot(paths["metrics"])
    tracer.save(paths["trace"])
    if trace_tag:
        tagged = str(out_dir / f"trace_{trace_tag}.json")
        paths["trace_tagged"] = tagged
        tracer.save(tagged)
    with open(paths["prometheus"], "w") as f:
        f.write(registry.to_prometheus())
    return paths
