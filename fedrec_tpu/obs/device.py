"""Device-layer watchdogs: XLA recompile accounting + HBM gauges.

Two signals the host-side registry/tracer could not see before:

* :class:`CompileWatchdog` — counts XLA backend compiles via
  ``jax.monitoring`` and attributes each to the jitted callable (and the
  argument-shape signature) that was executing when it fired.  A compile
  for a *new* (fn, shapes) signature is warmup; a compile for an
  already-seen signature is a RECOMPILE — the cache-thrash case a
  recompile storm is made of.  Storms (``storm_threshold`` compiles
  within ``storm_window_s``) bump a counter and warn on stderr with the
  shape provenance, because the usual cause — a batch dimension that
  varies per step — is invisible in wall-time metrics until the run is
  10× slower than the bench said.
* :func:`sample_device_memory` — ``device.memory_stats()`` gauges
  (bytes_in_use / peak / limit) sampled at round boundaries and stamped
  into the trace as an instant event inside the current ``fed_round``
  span.  On backends without allocator stats (CPU) it is a no-op.

``jax`` is imported lazily inside functions — the obs package stays
importable (and cheap) on artifact-reading boxes with no JAX.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Sequence

from fedrec_tpu.obs.registry import MetricsRegistry, get_registry

# substring match: the event is '/jax/core/compile/backend_compile_duration'
# on jax 0.4.x; newer jaxlibs rename the suffix but keep the stem
_COMPILE_EVENT_STEM = "backend_compile"

_MEMORY_STAT_KEYS = ("bytes_in_use", "peak_bytes_in_use", "bytes_limit")

_tls = threading.local()
_install_lock = threading.Lock()
_listener_installed = False
_active: "CompileWatchdog | None" = None


def _call_stack() -> list:
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = _tls.stack = []
    return stack


def _on_event_duration(name: str, dur: float, **_kw: Any) -> None:
    # events fired by the cost hook's own AOT re-compile are telemetry
    # overhead, not program compiles — without the suppression they would
    # double-count xla.compile_seconds_total (and read as <unwatched>)
    if getattr(_tls, "suppress_compile_events", False):
        return
    wd = _active
    if wd is not None and _COMPILE_EVENT_STEM in name:
        wd._on_compile(float(dur))


def shape_signature(args: tuple, kwargs: dict | None = None) -> str:
    """Compact dtype[shape] signature of a call's array leaves — the
    provenance string a recompile is attributed to."""
    import jax

    parts: list[str] = []
    leaves = jax.tree_util.tree_leaves((args, kwargs or {}))
    for leaf in leaves[:64]:
        shape = getattr(leaf, "shape", None)
        if shape is None:
            parts.append(type(leaf).__name__)
        else:
            dt = str(getattr(leaf, "dtype", "?"))
            parts.append(f"{dt}[{','.join(str(d) for d in shape)}]")
    if len(leaves) > 64:
        parts.append(f"…+{len(leaves) - 64}")
    return " ".join(parts)


class CompileWatchdog:
    """Recompilation accounting with shape provenance.

    ``watch(fn, name)`` wraps a (jitted) callable; while a wrapped call is
    on the stack, any backend compile that fires is attributed to it.
    One module-level ``jax.monitoring`` listener is installed on first
    ``install()`` and dispatches to the ACTIVE watchdog (swap-able, so
    tests get fresh counts without leaking listeners — jax offers no
    per-listener removal).
    """

    def __init__(
        self,
        registry: MetricsRegistry | None = None,
        storm_threshold: int = 5,
        storm_window_s: float = 60.0,
        provenance_capacity: int = 100,
        cost_cb: Callable | None = None,
    ):
        self.registry = registry or get_registry()
        # obs.perf's compile-cost hook: called as cost_cb(fn, args,
        # kwargs, name) after any watched call during which a NEW
        # compilation fired, so the compiled executable's cost_analysis
        # (FLOPs / bytes accessed) can be recorded with fn provenance.
        # None (the default) keeps the pre-perf watch() behavior exactly.
        self.cost_cb = cost_cb
        self.storm_threshold = max(int(storm_threshold), 1)
        self.storm_window_s = float(storm_window_s)
        self._c_compiles = self.registry.counter(
            "xla.compiles_total", "XLA backend compiles, by watched callable",
            labels=("fn",),
        )
        self._c_recompiles = self.registry.counter(
            "xla.recompiles_total",
            "compiles for an already-seen (fn, shapes) signature — cache "
            "thrash, not warmup", labels=("fn",),
        )
        self._c_compile_secs = self.registry.counter(
            "xla.compile_seconds_total", "wall seconds spent in backend compiles"
        )
        self._c_storms = self.registry.counter(
            "xla.recompile_storms_total",
            f"windows with >= threshold compiles in {storm_window_s:g}s",
        )
        self._lock = threading.Lock()
        self._seen: set[tuple[str, str]] = set()
        self._provenance: list[dict] = []  # capacity-trimmed
        self._provenance_capacity = provenance_capacity
        self._recent: list[float] = []  # compile timestamps for storm detection
        self._storm_warned_at = 0.0

    # ---------------------------------------------------------- listener
    def install(self) -> "CompileWatchdog | None":
        """Make this the active watchdog; returns the previous one."""
        global _listener_installed, _active
        with _install_lock:
            if not _listener_installed:
                import jax

                jax.monitoring.register_event_duration_secs_listener(
                    _on_event_duration
                )
                _listener_installed = True
            prev, _active = _active, self
            return prev

    def _on_compile(self, dur_s: float) -> None:
        # one jitted dispatch can fire SEVERAL backend_compile events
        # (helper subcomputations compile separately) — so a "compilation"
        # is counted once per watched CALL, on its first event; later
        # events in the same call only accumulate compile seconds.
        stack = _call_stack()
        frame = stack[-1] if stack else None
        now = time.monotonic()
        new_compilation = frame is not None and not frame["counted"]
        recompile = False
        storm = False
        if frame is not None:
            frame["counted"] = True
        fn = frame["fn"] if frame else "<unwatched>"
        if new_compilation and frame["sig"] is None:
            # lazy: the signature is only materialized when a compile
            # actually fires — compile events run synchronously inside the
            # watched call, so the args are still live and readable
            frame["sig"] = shape_signature(frame["args"], frame["kwargs"])
        with self._lock:
            if new_compilation:
                token = (fn, frame["sig"])
                recompile = token in self._seen
                self._seen.add(token)
                self._provenance.append({
                    "fn": fn, "shapes": frame["sig"], "dur_s": dur_s,
                    "recompile": recompile, "t": now,
                })
                if len(self._provenance) > self._provenance_capacity:
                    del self._provenance[0]
                # storm = many compilations of the SAME callable inside the
                # window (beyond its bucketed-shape warmup); unrelated
                # programs warming up together are not a storm
                self._recent.append((now, fn))
                cutoff = now - self.storm_window_s
                self._recent = [e for e in self._recent if e[0] >= cutoff]
                n_fn = sum(1 for _, f in self._recent if f == fn)
                storm = (
                    n_fn >= self.storm_threshold
                    and now - self._storm_warned_at > self.storm_window_s
                )
                if storm:
                    self._storm_warned_at = now
        if new_compilation:
            self._c_compiles.inc(fn=fn)
            if recompile:
                self._c_recompiles.inc(fn=fn)
        self._c_compile_secs.inc(dur_s)
        if storm:
            self._c_storms.inc()
            import sys

            recent = [
                p for p in self.provenance() if p["fn"] == fn
            ][-self.storm_threshold:]
            shapes = "; ".join(p["shapes"][:80] for p in recent)
            print(
                f"[obs.device] RECOMPILE STORM: {fn} compiled {n_fn} times "
                f"within {self.storm_window_s:g}s — a per-step varying "
                f"shape is defeating the jit cache. Recent shapes: {shapes}",
                file=sys.stderr,
            )

    # -------------------------------------------------------------- watch
    def watch(self, fn: Callable, name: str) -> Callable:
        """Wrap ``fn`` so compiles during its calls carry (name, shapes)
        provenance. Pass-through otherwise (donation, outputs untouched)."""

        def wrapped(*args, **kwargs):
            stack = _call_stack()
            # sig stays None until a compile event actually fires: after
            # warmup no event ever does, so the hot dispatch path pays one
            # dict append instead of a tree walk + string format per call
            frame = {
                "fn": name,
                "sig": None,
                "args": args,
                "kwargs": kwargs,
                "counted": False,
            }
            stack.append(frame)
            try:
                return fn(*args, **kwargs)
            finally:
                stack.pop()
                # compile-cost hook (obs.perf): only after a call that
                # actually compiled — the steady-state dispatch path never
                # reaches it. Guarded: telemetry must never displace the
                # call's own result or exception.
                if frame["counted"] and self.cost_cb is not None:
                    # the hook's lowered.compile() is an AOT compile that
                    # does NOT share the jit dispatch cache — its own
                    # backend_compile events must not count as program
                    # compiles (suppressed above)
                    _tls.suppress_compile_events = True
                    try:
                        self.cost_cb(fn, args, kwargs, name)
                    except Exception:  # noqa: BLE001
                        pass
                    finally:
                        _tls.suppress_compile_events = False

        wrapped.__name__ = f"watched_{name}"
        return wrapped

    # ------------------------------------------------------------ inspect
    def compiles(self, fn: str) -> int:
        return int(self._c_compiles.value(fn=fn))

    def recompiles(self, fn: str) -> int:
        return int(self._c_recompiles.value(fn=fn))

    def provenance(self) -> list[dict]:
        with self._lock:
            return list(self._provenance)


def set_active_watchdog(wd: "CompileWatchdog | None") -> "CompileWatchdog | None":
    """Swap the active watchdog without installing (tests); returns prev."""
    global _active
    with _install_lock:
        prev, _active = _active, wd
        return prev


# ------------------------------------------------------------------ memory
def sample_device_memory(
    registry: MetricsRegistry | None = None,
    tracer: Any = None,
    devices: Sequence[Any] | None = None,
    **annotations: Any,
) -> int:
    """Sample per-device allocator stats into gauges (+ one trace instant
    per device, so the sample lands inside the current ``fed_round`` span).
    Returns how many devices reported stats (0 on CPU — a clean no-op)."""
    registry = registry or get_registry()
    if devices is None:
        import jax

        devices = jax.local_devices()
    sampled = 0
    for d in devices:
        try:
            stats = d.memory_stats()
        except Exception:  # backend without allocator stats
            stats = None
        if not stats:
            continue
        sampled += 1
        dev = str(getattr(d, "id", sampled - 1))
        ev: dict[str, Any] = {"device": dev, **annotations}
        for key in _MEMORY_STAT_KEYS:
            if key in stats:
                registry.gauge(
                    f"device.memory.{key}",
                    "device allocator stats sampled at round boundaries",
                    labels=("device",),
                ).set(float(stats[key]), device=dev)
                ev[key] = int(stats[key])
        if tracer is not None:
            tracer.instant("hbm", **ev)
    return sampled
