"""Training-health monitor + flight recorder: NaN/divergence forensics.

The in-graph numeric sentry (``train.step`` with ``obs.health.sentry``)
makes every jitted step return a compact per-client health vector — loss,
global grad-norm, update-norm, param-norm, a non-finite flag, and (under
DP-SGD) the per-example clip-rate.  This module is the HOST side of that
contract:

* :class:`HealthMonitor` digests the round's fetched health arrays —
  publishes them as registry histograms/gauges, flags outlier clients
  (round-mean update-norm > k·median of the cohort: the
  poisoning/divergence triage signal), and decides whether the round
  tripped a trigger (any non-finite cell, or a loss spike vs the
  trailing-window mean).
* :class:`FlightRecorder` keeps a bounded ring of the last N
  (batch, metadata) records plus the round/chunk-entry state; on a
  trigger it dumps the offending batch, a params/opt-state checkpoint
  (flax msgpack), the registry snapshot, and a replay manifest into
  ``obs.dir/flightrec/``.  ``fedrec-obs replay`` re-executes the dumped
  steps on CPU to confirm/bisect — federated failures are per-client and
  non-reproducible after the fact unless the exact (state, batch, rng)
  triple is preserved (the FedJAX/FL_PyTorch lesson).

Module-level imports stay JAX-free (the obs package contract); the dump
path imports flax lazily.
"""

from __future__ import annotations

import json
import time
from collections import deque
from pathlib import Path
from typing import Any, Mapping

import numpy as np

from fedrec_tpu.obs.registry import MetricsRegistry, get_registry

# log-spaced norm buckets: grad/update/param norms span decades; latency
# buckets would put every observation in one bin
NORM_BUCKETS = (
    1e-4, 1e-3, 1e-2, 0.1, 0.5, 1.0, 5.0, 10.0, 50.0, 100.0, 1e3, 1e4, 1e6
)
CLIP_RATE_BUCKETS = (0.01, 0.05, 0.1, 0.25, 0.5, 0.75, 0.9, 0.99, 1.0)

class TrainingHealthError(RuntimeError):
    """Raised (after the flight-recorder dump) when the numeric sentry
    sees a non-finite step and ``obs.health.abort_on_nonfinite`` is set."""


def _observe_array(hist, arr: np.ndarray) -> None:
    """Publish every cell of ``arr`` into a registry histogram in ONE
    vectorized pass + one lock acquire (a per-cell ``observe()`` loop
    costs milliseconds per chunk on the round-critical host path).
    ``searchsorted(side='left')`` matches ``observe``'s inclusive-upper-
    bound ``bisect_left``; +inf (and nan, which compares unordered) land
    in the overflow bucket."""
    flat = np.asarray(arr, np.float64).reshape(-1)
    if flat.size == 0:
        return
    bounds = np.asarray(hist.buckets)
    idx = np.searchsorted(bounds, flat, side="left")
    counts = np.bincount(idx, minlength=len(bounds) + 1)
    hist.merge_counts(counts.tolist(), float(flat.sum()), int(flat.size))


class HealthMonitor:
    """Round-cadence digest of the sentry's health arrays.

    ``check()`` takes ``(rounds, steps, clients)``-shaped arrays (a
    host-driven round passes rounds=1) so the host-driven loop and the
    rounds-in-jit chunk share one code path — and one trigger policy.
    """

    def __init__(self, health_cfg: Any, registry: MetricsRegistry | None = None):
        self.cfg = health_cfg
        self.registry = registry or get_registry()
        r = self.registry
        self._h_grad = r.histogram(
            "health.grad_norm", "per-client per-step global grad norm "
            "(post-noise, pre-sync)", buckets=NORM_BUCKETS,
        )
        self._h_update = r.histogram(
            "health.update_norm", "per-client per-step optimizer-update norm",
            buckets=NORM_BUCKETS,
        )
        self._g_param = r.gauge(
            "health.param_norm", "last observed per-client param norm (max)"
        )
        self._c_nonfinite = r.counter(
            "health.nonfinite_steps_total",
            "step×client cells whose loss/grad/update/params went non-finite",
        )
        self._c_outliers = r.counter(
            "health.outlier_clients_total",
            "client-rounds whose mean update-norm exceeded k·cohort-median",
        )
        self._g_outliers = r.gauge(
            "health.outlier_clients", "outlier clients in the last round"
        )
        self._h_clip = r.histogram(
            "privacy.clip_rate",
            "per-step fraction of per-example grads clipped to C (dpsgd)",
            buckets=CLIP_RATE_BUCKETS,
        )
        self._g_clip = r.gauge(
            "privacy.clip_rate_last",
            "clip-rate of the last observed step (mean over clients)",
        )
        self._g_max_norm = r.gauge(
            "privacy.max_grad_norm",
            "largest pre-clip per-example grad norm in the last step (max "
            "over clients) — how far above/below C the raw grads sit",
        )
        self._loss_window: deque[float] = deque(
            maxlen=max(int(getattr(health_cfg, "spike_window", 8)), 1)
        )
        # outliers found by the most recent check() (post-ignore): the
        # Trainer's quarantine/rollback path reads this — an outlier alone
        # is not a dump trigger, but under fed.robust.recover it is a
        # quarantine trigger
        self.last_outliers: list[dict] = []
        # quality-outlier clients from the obs.quality per-client digest
        # (eval cadence): published here so triage tooling reads norm- AND
        # quality-flags off one monitor. Informational by contract — the
        # recovery path keys on update norms only, a quality dip NEVER
        # quarantines (fedrec_tpu.obs.quality.QualityMonitor.digest_clients)
        self.last_quality_outliers: list[dict] = []

    # ------------------------------------------------------------ publish
    def publish_clip_rate(self, clip_rates: np.ndarray) -> None:
        """Publish dpsgd clip-rate observations: histogram per cell, gauge
        holds the last step's mean — the value the clip-rate correctness
        test pins exactly."""
        arr = np.asarray(clip_rates, np.float64)
        flat = arr.reshape(-1)
        if flat.size == 0:
            return
        _observe_array(self._h_clip, flat)
        last_step = arr.reshape(-1, arr.shape[-1])[-1] if arr.ndim >= 2 else flat
        self._g_clip.set(float(np.mean(last_step)))

    # -------------------------------------------------------------- check
    def check(
        self,
        start_round: int,
        rows: Mapping[str, np.ndarray],
        round_losses: list[float],
        ignore_clients: set[int] | None = None,
    ) -> dict | None:
        """Digest one round's (or chunk's) health arrays.

        ``rows`` values are shaped ``(rounds, steps, clients)``;
        ``round_losses`` has one mean loss per round.  Publishes registry
        instruments and returns a trigger dict (``kind`` ∈ {"nonfinite",
        "loss_spike"}) or None.  Non-finite wins over a spike — it is the
        root-cause signal.

        ``ignore_clients`` (the Trainer's quarantine set) suppresses
        triggers AND outlier flags from those clients: a quarantined
        client's weight is already 0, so its (expected) bad numbers must
        not re-trigger the rollback it caused — and must not pollute the
        cohort median other clients are judged against.  The outlier list
        of the last check (post-ignore) is kept on ``self.last_outliers``
        for the recovery path.
        """
        ignore = ignore_clients or set()
        arrays = {
            k: np.asarray(v, np.float64) for k, v in rows.items() if v is not None
        }
        trigger: dict | None = None

        grad = arrays.get("health.grad_norm")
        upd = arrays.get("health.update_norm")
        param = arrays.get("health.param_norm")
        if grad is not None:
            _observe_array(self._h_grad, grad)
        if upd is not None:
            _observe_array(self._h_update, upd)
        if param is not None and param.size:
            last = param.reshape(-1, param.shape[-1])[-1]
            self._g_param.set(float(np.max(last)))
        if "health.clip_rate" in arrays:
            self.publish_clip_rate(arrays["health.clip_rate"])
        if "health.clip_max_norm" in arrays:
            mx = arrays["health.clip_max_norm"]
            if mx.size:
                self._g_max_norm.set(
                    float(np.max(mx.reshape(-1, mx.shape[-1])[-1]))
                )

        # ---- outlier clients: round-mean update norm vs cohort median.
        # The median spans only eligible (non-ignored) clients with FINITE
        # norms: one NaN client would otherwise NaN the median and hide
        # every real outlier in the same round.
        k = float(getattr(self.cfg, "outlier_k", 0.0) or 0.0)
        outliers: list[dict] = []
        if upd is not None and k > 0 and upd.ndim == 3 and upd.shape[-1] >= 2:
            eligible = np.array(
                [c not in ignore for c in range(upd.shape[-1])], bool
            )
            for r in range(upd.shape[0]):
                per_client = upd[r].mean(axis=0)  # (clients,)
                base = per_client[eligible & np.isfinite(per_client)]
                if base.size < 2:
                    continue
                med = float(np.median(base))
                if med > 0 and np.isfinite(med):
                    for c in np.nonzero(per_client > k * med)[0]:
                        if not eligible[c]:
                            continue
                        outliers.append({
                            "round": start_round + r,
                            "client": int(c),
                            "update_norm": float(per_client[c]),
                            "cohort_median": med,
                        })
        self.last_outliers = outliers
        if outliers:
            self._c_outliers.inc(len(outliers))
        self._g_outliers.set(float(len(set(
            (o["round"], o["client"]) for o in outliers
        ))))

        # ---- non-finite sentinel (counter counts EVERY bad cell; the
        # trigger comes from the first cell of a non-ignored client)
        nf = arrays.get("health.nonfinite")
        if nf is not None and nf.sum() > 0:
            self._c_nonfinite.inc(float(nf.sum()))
            nf = nf.copy()
            for c in ignore:
                if 0 <= c < nf.shape[-1]:
                    nf[..., c] = 0
        if nf is not None and nf.sum() > 0:
            r, s, c = (int(i[0]) for i in np.nonzero(nf))
            detail = {
                key: float(arrays[key][r, s, c])
                for key in ("health.grad_norm", "health.update_norm",
                            "health.param_norm")
                if key in arrays
            }
            trigger = {
                "kind": "nonfinite",
                "round": start_round + r,
                "step": s,
                "client": c,
                "total_nonfinite_cells": float(nf.sum()),
                "detail": detail,
            }

        # ---- loss-spike divergence predicate (trailing-window mean)
        factor = float(getattr(self.cfg, "spike_factor", 0.0) or 0.0)
        for i, rl in enumerate(round_losses):
            if (
                trigger is None
                and factor > 0
                and len(self._loss_window) == self._loss_window.maxlen
                and np.isfinite(rl)
            ):
                trailing = float(np.mean(self._loss_window))
                if rl > factor * trailing:
                    trigger = {
                        "kind": "loss_spike",
                        "round": start_round + i,
                        "step": None,
                        "round_loss": float(rl),
                        "trailing_mean": trailing,
                        "factor": factor,
                    }
            if np.isfinite(rl):
                self._loss_window.append(float(rl))

        if outliers and trigger is None:
            # not a dump trigger, but worth a line: the operator's first
            # hint that one client is poisoning/diverging the cohort
            worst = max(outliers, key=lambda o: o["update_norm"])
            print(
                f"[health] outlier client(s) {sorted(set(o['client'] for o in outliers))}"
                f" in round {worst['round']}: update_norm "
                f"{worst['update_norm']:.3g} vs cohort median "
                f"{worst['cohort_median']:.3g} (k={k})"
            )
        if trigger is not None and outliers:
            trigger["outliers"] = outliers
        return trigger


class FlightRecorder:
    """Bounded ring of (batch, rng/step metadata) + chunk-entry state.

    ``start_chunk`` is called at every round (host-driven) or chunk
    (rounds-in-jit) entry with a HOST copy of the pre-chunk client state —
    replay must start from the state the offending step actually saw, and
    the device buffers may be donated away by the time a trigger fires.
    ``record`` appends one per-step batch record (numpy references, no
    copies).  ``dump`` writes the whole forensic bundle.
    """

    def __init__(self, ring_size: int = 16, dump_policy: str = "first",
                 dump_table_max_mb: int = 512):
        self.ring_size = max(int(ring_size), 1)
        self.dump_policy = dump_policy
        self.dump_table_max_mb = dump_table_max_mb
        self._ring: deque[dict] = deque(maxlen=self.ring_size)
        self._state_host: Any = None
        self._chunk_start_round: int | None = None
        self._weights: dict[int, list[float]] = {}
        self._records_seen = 0
        self._dumped_kinds: set[str] = set()
        self.dump_count = 0
        self.last_dump_dir: Path | None = None

    # ------------------------------------------------------------ record
    def start_chunk(
        self,
        round_idx: int,
        state_host: Any,
        weights_by_round: Mapping[int, np.ndarray] | None = None,
    ) -> None:
        self._ring.clear()
        self._records_seen = 0
        self._chunk_start_round = int(round_idx)
        self._state_host = state_host
        self._weights = {
            int(r): np.asarray(w, np.float64).tolist()
            for r, w in (weights_by_round or {}).items()
        }

    def record(self, batch: Mapping[str, Any], round_idx: int,
               epoch_idx: int, step_idx: int) -> None:
        self._records_seen += 1
        self._ring.append({
            "round": int(round_idx),
            "epoch": int(epoch_idx),
            "step": int(step_idx),
            "batch": {k: np.asarray(v) for k, v in batch.items()},
        })

    # -------------------------------------------------------------- dump
    def dump(
        self,
        out_dir: str | Path,
        trigger: Mapping[str, Any],
        cfg: Any = None,
        registry: MetricsRegistry | None = None,
        table: Any = None,
        meta: Mapping[str, Any] | None = None,
    ) -> Path | None:
        """Write the forensic bundle; returns the dump directory (None when
        the dump policy suppressed a repeat dump).

        ``dump_policy='first'`` suppresses repeats PER TRIGGER KIND: an
        early loss-spike dump must never swallow the later non-finite
        dump — the NaN's forensics are the ones the operator actually
        needs, and the spike-round state cannot replay the NaN round."""
        kind = str(trigger.get("kind", ""))
        if self.dump_policy == "first" and kind in self._dumped_kinds:
            return None
        self._dumped_kinds.add(kind)
        self.dump_count += 1
        base = Path(out_dir)
        dump_dir = base if self.dump_count == 1 else base.with_name(
            f"{base.name}_{self.dump_count}"
        )
        dump_dir.mkdir(parents=True, exist_ok=True)

        manifest: dict[str, Any] = {
            "kind": "flight_recorder_dump",
            "created_unix": time.time(),
            "trigger": dict(trigger),
            "chunk_start_round": self._chunk_start_round,
            "weights": self._weights,
            "ring_size": self.ring_size,
            # False when the ring dropped early-chunk steps: replay then
            # starts mid-chunk against the chunk-entry state (approximate)
            "ring_complete": self._records_seen <= self.ring_size,
            "records": [],
        }
        if meta:
            manifest.update(dict(meta))
        if cfg is not None:
            manifest["config"] = cfg.to_dict()

        for i, rec in enumerate(self._ring):
            fname = f"batch_{i:03d}.npz"
            np.savez(dump_dir / fname, **rec["batch"])
            manifest["records"].append({
                "round": rec["round"], "epoch": rec["epoch"],
                "step": rec["step"], "file": fname,
            })

        manifest["state_file"] = None
        if self._state_host is not None:
            from flax import serialization  # lazy: heavy import, dump-only

            (dump_dir / "state.msgpack").write_bytes(
                serialization.to_bytes(self._state_host)
            )
            manifest["state_file"] = "state.msgpack"

        manifest["table_file"] = None
        if table is not None:
            arr = np.asarray(table)
            if arr.nbytes <= self.dump_table_max_mb * 1e6:
                np.save(dump_dir / "table.npy", arr)
                manifest["table_file"] = "table.npy"
            else:
                manifest["table_skipped_mb"] = round(arr.nbytes / 1e6, 1)

        manifest["registry_file"] = None
        if registry is not None:
            (dump_dir / "registry.json").write_text(
                json.dumps(registry.snapshot())
            )
            manifest["registry_file"] = "registry.json"

        # offending record, if the ring still holds it
        off = None
        tr_round, tr_step = trigger.get("round"), trigger.get("step")
        for rec in manifest["records"]:
            if rec["round"] == tr_round and (
                tr_step is None or rec["step"] == tr_step
            ):
                off = rec
                break
        manifest["offending"] = off

        # manifest last: its presence marks the dump complete
        (dump_dir / "manifest.json").write_text(json.dumps(manifest, indent=2))
        self.last_dump_dir = dump_dir
        return dump_dir
