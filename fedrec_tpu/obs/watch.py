"""Continuous watch layer: declarative SLOs, burn rates, anomaly detection.

Every observability surface before this module was post-hoc: reports
render after the run, the banked gates judge between runs.  The watch
layer is the LIVE half — it notices a burning SLO or an anomalous
round-cadence series mid-run, raises a first-class alert through
:mod:`fedrec_tpu.obs.alerts`, and resolves it when the signal recovers:

* **Declarative SLOs** (``obs.slo.objectives``) — objectives over
  metrics the registry already publishes, parsed by
  :func:`parse_slo_spec`.  Histograms are read as per-evaluation bucket
  DELTAS (this round's quantile, not the lifetime distribution),
  counters as deltas, gauges and MetricLogger record keys at face
  value.
* **Multi-window burn rates** (:class:`BurnRateEvaluator`) — each
  evaluation scores one good/bad event per objective; an alert fires
  Google-SRE style when the burn rate (bad fraction / error budget)
  exceeds ``fast_burn`` over the fast window AND ``slow_burn`` over the
  slow window.  Windows are counted in evaluations, so one spec scales
  from round cadence (Trainer) to heartbeat cadence (fedrec-serve) to
  commit cadence (agg server).
* **Streaming anomaly detection** (:class:`AnomalyDetector`) — an EWMA
  baseline + MAD robust z-score per round-cadence series, the net that
  flags regressions no explicit SLO names.
* **One trigger path** — the four legacy ad-hoc triggers (health
  loss-spike/outlier, quality outlier digest, serving drift-probe
  breach, perf efficiency drop) pulse through the same engine; the perf
  drop-capture arms off the alert's firing transition.
* **Fleet rules** (:class:`FleetRules`) — evaluated collector-side per
  telemetry push: persistent straggler (naming the worker), world below
  target, quorum-wait growth, stalled commit version.

Nothing here is constructed unless ``obs.slo.enabled`` is set; a
disabled run registers no ``alert.*`` instrument and executes the
byte-identical pre-watch programs (pinned in ``tests/test_watch.py``).
The module imports no JAX (the obs package contract).
Metric catalogue: ``docs/OBSERVABILITY.md`` §11; runbook:
``docs/OPERATIONS.md`` §7g.
"""

from __future__ import annotations

import math
import re
import statistics
from collections import deque
from dataclasses import dataclass, field
from typing import Any

from fedrec_tpu.obs.alerts import AlertEngine
from fedrec_tpu.obs.registry import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    get_registry,
    quantile_from_counts,
)

# the one perf-drop alert key: PerfMonitor's capture arms when THIS key
# transitions to firing (fedrec_tpu.obs.perf)
PERF_DROP_KEY = "perf:efficiency_drop"

_OBJECTIVE_RE = re.compile(
    r"^(?P<name>[A-Za-z0-9_\-]+)"
    r":(?P<metric>[a-zA-Z0-9_.:@]+?)"
    r"(?:\{(?P<labels>[^}]*)\})?"
    r"(?::p(?P<q>[0-9]+(?:\.[0-9]+)?))?"
    r"(?P<op><=|>=|<|>)"
    r"(?P<thr>-?[0-9.eE+\-]+)"
    r"(?:@(?P<target>0?\.[0-9]+|1(?:\.0*)?))?$"
)


@dataclass
class SloObjective:
    """One parsed ``obs.slo.objectives`` entry."""

    name: str
    metric: str
    op: str
    threshold: float
    labels: dict[str, str] = field(default_factory=dict)
    quantile: float | None = None      # pQQ -> 0.QQ; None = gauge/mean read
    target: float = 0.99               # per-objective error-budget target

    def good(self, value: float) -> bool:
        if self.op == "<":
            return value < self.threshold
        if self.op == "<=":
            return value <= self.threshold
        if self.op == ">":
            return value > self.threshold
        return value >= self.threshold

    def describe(self) -> str:
        lbl = (
            "{" + ",".join(f"{k}={v}" for k, v in sorted(self.labels.items()))
            + "}" if self.labels else ""
        )
        q = f":p{self.quantile * 100:g}" if self.quantile is not None else ""
        return f"{self.metric}{lbl}{q}{self.op}{self.threshold:g}"


def parse_slo_spec(spec: str, default_target: float = 0.99) -> list[SloObjective]:
    """``obs.slo.objectives`` -> objectives; raises ValueError naming the
    malformed entry (grammar: ``name:metric[{k=v,..}][:pQQ]OPthr[@target]``)."""
    out: list[SloObjective] = []
    seen: set[str] = set()
    for raw in str(spec or "").split(";"):
        part = "".join(raw.split())  # whitespace is never significant
        if not part:
            continue
        m = _OBJECTIVE_RE.match(part)
        if m is None:
            raise ValueError(
                f"bad obs.slo.objectives entry {raw.strip()!r} — expected "
                "name:metric[{label=value,...}][:pQQ]<op>threshold[@target] "
                "with <op> one of < <= > >= "
                "(e.g. round_time:train.round_seconds:p95<2.5)"
            )
        labels: dict[str, str] = {}
        for pair in (m.group("labels") or "").split(","):
            if not pair:
                continue
            if "=" not in pair:
                raise ValueError(
                    f"bad obs.slo.objectives label {pair!r} in {raw.strip()!r}"
                    " — labels are comma-separated key=value pairs"
                )
            k, v = pair.split("=", 1)
            labels[k] = v
        q = m.group("q")
        quantile = None
        if q is not None:
            quantile = float(q) / 100.0
            if not 0.0 < quantile <= 1.0:
                raise ValueError(
                    f"bad obs.slo.objectives quantile p{q} in {raw.strip()!r}"
                    " — must lie in (0, 100]"
                )
        name = m.group("name")
        if name in seen:
            raise ValueError(
                f"duplicate obs.slo.objectives name {name!r} — each "
                "objective keys its own alert and burn-rate gauges"
            )
        seen.add(name)
        target = float(m.group("target") or default_target)
        if not 0.0 < target < 1.0:
            raise ValueError(
                f"bad obs.slo.objectives target {target} for {name!r} — "
                "must lie in (0, 1) (the error budget is 1 - target)"
            )
        out.append(SloObjective(
            name=name, metric=m.group("metric"), op=m.group("op"),
            threshold=float(m.group("thr")), labels=labels,
            quantile=quantile, target=target,
        ))
    return out


class BurnRateEvaluator:
    """Good/bad event window + the two burn rates for one objective.

    ``burn = bad_fraction / (1 - target)`` over each window; the alert
    condition is BOTH windows over their thresholds (the fast window
    catches the page-worthy spike, the slow window keeps a brief blip
    from paging — the Google-SRE multi-window idiom, with windows in
    evaluations instead of wall minutes so the thresholds scale with
    cadence)."""

    def __init__(
        self,
        objective: SloObjective,
        fast_window: int,
        slow_window: int,
        fast_burn: float,
        slow_burn: float,
    ):
        self.objective = objective
        self.fast_window = max(int(fast_window), 1)
        self.slow_window = max(int(slow_window), self.fast_window)
        self.fast_burn = float(fast_burn)
        self.slow_burn = float(slow_burn)
        self._events: deque[bool] = deque(maxlen=self.slow_window)

    def observe(self, value: float) -> dict:
        """Score one evaluation's value; returns the burn verdict."""
        self._events.append(not self.objective.good(float(value)))
        return self.verdict()

    def verdict(self) -> dict:
        budget = max(1.0 - self.objective.target, 1e-9)
        ev = list(self._events)
        fast = ev[-self.fast_window:]
        fast_rate = sum(fast) / len(fast) if fast else 0.0
        slow_rate = sum(ev) / len(ev) if ev else 0.0
        fast_burn = fast_rate / budget
        slow_burn = slow_rate / budget
        return {
            "fast_burn": fast_burn,
            "slow_burn": slow_burn,
            "breached": bool(
                ev
                and fast_burn >= self.fast_burn
                and slow_burn >= self.slow_burn
            ),
        }


class AnomalyDetector:
    """EWMA baseline + MAD robust z-score over round-cadence series.

    Per series: the baseline is an exponentially weighted moving average,
    the scale a median-absolute-deviation over the trailing residual
    window (``1.4826 * MAD`` estimates sigma robustly — one outlier
    cannot inflate its own yardstick the way a stddev would).  A point
    fires when ``|x - ewma - median(residuals)| / scale`` exceeds ``z``
    after ``warmup`` observations; the baseline keeps adapting through
    an anomaly, so a true level shift resolves itself once the new
    regime becomes the baseline."""

    _MIN_RESIDUALS = 4

    def __init__(
        self,
        alpha: float = 0.3,
        window: int = 32,
        z: float = 6.0,
        warmup: int = 8,
    ):
        self.alpha = min(max(float(alpha), 0.0), 1.0)
        self.window = max(int(window), self._MIN_RESIDUALS)
        self.z = float(z)
        self.warmup = max(int(warmup), 1)
        self._state: dict[str, dict] = {}

    def observe(self, key: str, value: float) -> dict | None:
        """Feed one point; returns anomaly info when it fires, else None."""
        value = float(value)
        if not math.isfinite(value):
            return None
        st = self._state.get(key)
        if st is None:
            self._state[key] = {
                "ewma": value, "resid": deque(maxlen=self.window), "n": 1,
            }
            return None
        residual = value - st["ewma"]
        fired: dict | None = None
        resid = st["resid"]
        if st["n"] >= self.warmup and len(resid) >= self._MIN_RESIDUALS:
            med = statistics.median(resid)
            mad = statistics.median(abs(r - med) for r in resid)
            # absolute floor keeps a constant series (MAD 0) from firing
            # on float jitter while a real step still registers
            scale = max(1.4826 * mad, 1e-9 * max(1.0, abs(st["ewma"])))
            zscore = abs(residual - med) / scale
            if zscore > self.z:
                fired = {
                    "series": key, "value": value, "z": zscore,
                    "baseline": st["ewma"],
                }
        st["ewma"] += self.alpha * residual
        resid.append(residual)
        st["n"] += 1
        return fired


class Watch:
    """The in-process watch: SLO burn rates + anomaly detection + the
    unified trigger pulses, all draining into one
    :class:`~fedrec_tpu.obs.alerts.AlertEngine`.

    ``evaluate()`` runs once per cadence tick (round / heartbeat /
    commit) with the tick's MetricLogger record (when one exists); the
    four legacy trigger paths pulse between ticks via the ``ingest_*``
    helpers and are scored at the next ``evaluate()``."""

    def __init__(
        self,
        slo_cfg: Any,
        watch_cfg: Any,
        *,
        registry: MetricsRegistry | None = None,
        tracer: Any = None,
        jsonl_path=None,
        jsonl_max_mb: float = 0.0,
    ):
        self.registry = registry or get_registry()
        self.objectives = parse_slo_spec(
            slo_cfg.objectives, float(slo_cfg.target)
        )
        self._evaluators = [
            BurnRateEvaluator(
                o,
                fast_window=slo_cfg.fast_window,
                slow_window=slo_cfg.slow_window,
                fast_burn=slo_cfg.fast_burn,
                slow_burn=slo_cfg.slow_burn,
            )
            for o in self.objectives
        ]
        self.engine = AlertEngine(
            registry=self.registry,
            tracer=tracer,
            pending_for=watch_cfg.pending_for,
            resolve_after=watch_cfg.resolve_after,
            flap_max=watch_cfg.flap_max,
            flap_window=watch_cfg.flap_window,
            history=watch_cfg.history,
            jsonl_path=jsonl_path,
            jsonl_max_mb=jsonl_max_mb,
        )
        self.anomaly: AnomalyDetector | None = None
        if watch_cfg.anomaly:
            self.anomaly = AnomalyDetector(
                alpha=watch_cfg.anomaly_alpha,
                window=watch_cfg.anomaly_window,
                z=watch_cfg.anomaly_z,
                warmup=watch_cfg.anomaly_warmup,
            )
        self.drift_churn_max = float(watch_cfg.drift_churn_max)
        # per-objective counter/histogram cursors for delta reads
        self._cursors: dict[str, Any] = {}
        self._pulses: dict[str, dict] = {}
        self._pulse_active: set[str] = set()
        self._c_evals = self.registry.counter(
            "alert.evaluations_total",
            "watch-layer evaluation ticks performed (round / heartbeat / "
            "commit cadence)",
        )
        self._g_burn = self.registry.gauge(
            "alert.slo_burn_rate",
            "last evaluated burn rate (bad fraction / error budget) per "
            "SLO objective and window",
            labels=("slo", "window"),
        )

    # ----------------------------------------------------------- plumbing
    def bind_perf(self, perf: Any) -> None:
        """Route the perf efficiency-drop trigger through the engine and
        arm the capture off the alert's FIRING transition (the unified
        replacement for PerfMonitor's private pending flag)."""
        perf.watch_hook = self.ingest_perf_drop

        def _arm(alert, event: str) -> None:
            if event == "firing" and alert.key == PERF_DROP_KEY:
                perf.arm_capture()

        self.engine.subscribe(_arm)

    def pulse(
        self,
        key: str,
        *,
        severity: str = "warning",
        summary: str = "",
        labels: dict[str, Any] | None = None,
        value: float | None = None,
        threshold: float | None = None,
    ) -> None:
        """Mark ``key`` breached for the CURRENT cadence tick; scored (and
        auto-cleared when the pulse stops repeating) at ``evaluate()``."""
        self._pulses[key] = {
            "severity": severity, "summary": summary,
            "labels": dict(labels or {}), "value": value,
            "threshold": threshold,
        }

    # ------------------------------------------------- unified trigger paths
    def ingest_health_trigger(self, trigger: dict | None) -> None:
        """HealthMonitor trigger dict (kind in nonfinite/loss_spike)."""
        if not trigger:
            return
        kind = str(trigger.get("kind", "trigger"))
        self.pulse(
            f"health:{kind}",
            severity="critical",
            summary=(
                f"health {kind} at round {trigger.get('round')}"
                + (f" client {trigger['client']}"
                   if trigger.get("client") is not None else "")
            ),
            labels={k: trigger[k] for k in ("round", "client")
                    if trigger.get(k) is not None},
            value=trigger.get("round_loss"),
        )

    def ingest_health_outliers(self, outliers: list[dict] | None) -> None:
        """HealthMonitor update-norm outlier list (poisoning triage)."""
        if not outliers:
            return
        worst = max(outliers, key=lambda o: o.get("update_norm", 0.0))
        clients = sorted(set(o["client"] for o in outliers))
        self.pulse(
            "health:outlier_clients",
            severity="warning",
            summary=(
                f"update-norm outlier client(s) {clients}: worst "
                f"{worst.get('update_norm', 0.0):.3g} vs cohort median "
                f"{worst.get('cohort_median', 0.0):.3g}"
            ),
            labels={"clients": ",".join(str(c) for c in clients)},
            value=worst.get("update_norm"),
            threshold=worst.get("cohort_median"),
        )

    def ingest_quality_outliers(self, outliers: list[dict] | None) -> None:
        """QualityMonitor per-client eval-AUC outlier digest."""
        if not outliers:
            return
        worst = min(outliers, key=lambda o: o.get("auc", 1.0))
        clients = sorted(set(o["client"] for o in outliers))
        self.pulse(
            "quality:outlier_clients",
            severity="warning",
            summary=(
                f"quality outlier client(s) {clients}: worst auc "
                f"{worst.get('auc', 0.0):.4f} vs cohort median "
                f"{worst.get('cohort_median', 0.0):.4f}"
            ),
            labels={"clients": ",".join(str(c) for c in clients)},
            value=worst.get("auc"),
            threshold=worst.get("cohort_median"),
        )

    def ingest_drift(self, stats: dict | None) -> None:
        """Serving drift-probe result (EmbeddingStore.metrics() keys or a
        DriftProbe.compare dict): breach on top-k rank churn past
        ``obs.watch.drift_churn_max``."""
        if not stats or self.drift_churn_max <= 0:
            return
        churn = stats.get("drift_rank_churn", stats.get("rank_churn"))
        if churn is None:
            return
        if float(churn) > self.drift_churn_max:
            self.pulse(
                "serve:drift",
                severity="critical",
                summary=(
                    f"pre-swap drift probe breach: rank churn "
                    f"{float(churn):.3f} > {self.drift_churn_max:g}"
                ),
                value=float(churn),
                threshold=self.drift_churn_max,
            )

    def ingest_perf_drop(
        self, round_idx: int, rate: float, trailing_mean: float
    ) -> None:
        """PerfMonitor efficiency-drop trigger (samples/s below the
        trailing-window mean); the capture arms when the alert FIRES."""
        self.pulse(
            PERF_DROP_KEY,
            severity="warning",
            summary=(
                f"round {round_idx} samples/s {rate:.1f} fell below the "
                f"trailing mean {trailing_mean:.1f}"
            ),
            labels={"round": round_idx},
            value=rate,
            threshold=trailing_mean,
        )

    # ----------------------------------------------------------- evaluation
    def _read_value(self, o: SloObjective, record: dict | None) -> float | None:
        if record is not None and not o.labels:
            v = record.get(o.metric)
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                return float(v)
        m = self.registry.get(o.metric)
        if m is None:
            return None
        try:
            if isinstance(m, Histogram):
                cell = m.cell(**o.labels)
                if cell is None:
                    return None
                prev = self._cursors.get(o.name) or {
                    "counts": [0] * len(cell["counts"]), "sum": 0.0,
                    "count": 0,
                }
                self._cursors[o.name] = cell
                dcounts = [c - p for c, p in zip(cell["counts"], prev["counts"])]
                dcount = cell["count"] - prev["count"]
                if dcount <= 0:
                    return None
                if o.quantile is not None:
                    return quantile_from_counts(o.quantile, m.buckets, dcounts)
                return (cell["sum"] - prev["sum"]) / dcount
            if isinstance(m, Counter):
                cur = m.value(**o.labels)
                prev = self._cursors.get(o.name, 0.0)
                self._cursors[o.name] = cur
                return cur - prev
            if isinstance(m, Gauge):
                return m.value(**o.labels)
        except ValueError:
            return None  # label set mismatch: the objective names labels
        return None       # the instrument doesn't carry

    def evaluate(self, record: dict | None = None) -> list[dict]:
        """One cadence tick: score every objective, run the anomaly
        detector over the record's series, drain trigger pulses.
        Returns the currently active alerts."""
        self._c_evals.inc()
        for ev in self._evaluators:
            o = ev.objective
            value = self._read_value(o, record)
            if value is None:
                continue
            verdict = ev.observe(value)
            self._g_burn.set(verdict["fast_burn"], slo=o.name, window="fast")
            self._g_burn.set(verdict["slow_burn"], slo=o.name, window="slow")
            self.engine.observe(
                f"slo:{o.name}",
                verdict["breached"],
                severity="critical",
                summary=(
                    f"SLO {o.name} burning: {o.describe()} "
                    f"(fast burn {verdict['fast_burn']:.1f}x, slow "
                    f"{verdict['slow_burn']:.1f}x budget)"
                ),
                labels={"slo": o.name, "metric": o.metric},
                value=value,
                threshold=o.threshold,
            )
        if self.anomaly is not None and record:
            for series, v in record.items():
                if series == "round" or isinstance(v, bool):
                    continue
                if not isinstance(v, (int, float)):
                    continue
                hit = self.anomaly.observe(series, float(v))
                self.engine.observe(
                    f"anomaly:{series}",
                    hit is not None,
                    severity="warning",
                    summary=(
                        f"anomalous {series}: {hit['value']:.6g} is "
                        f"{hit['z']:.1f} robust sigmas off the EWMA "
                        f"baseline {hit['baseline']:.6g}"
                    ) if hit else "",
                    labels={"series": series},
                    value=float(v),
                    pending_for=1,
                )
        pulses, self._pulses = self._pulses, {}
        for key in sorted(self._pulse_active | set(pulses)):
            info = pulses.get(key)
            alive = self.engine.observe(
                key,
                info is not None,
                pending_for=1,
                **(info or {}),
            )
            if alive is None:
                self._pulse_active.discard(key)
            else:
                self._pulse_active.add(key)
        return self.engine.active()


# --------------------------------------------------------------- fleet rules
class FleetRules:
    """Fleet-level watch rules, evaluated collector-side per telemetry
    push (the collector/membership service sees every worker, which no
    in-process watch does):

    * **persistent straggler** — two signatures, one alert, both vs
      ``fleet_straggler_factor`` x the fleet median for
      ``fleet_straggler_evals`` consecutive pushes, named in the alert:
      per-push mean round seconds (the sync/trainer signature — the
      live twin of the offline critical-path attribution) and push
      inter-arrival gap from the snapshot timestamps (the async
      signature: a worker that sleeps at the push boundary never
      inflates its own round_seconds, but cannot hide its arrival
      cadence);
    * **world below target** — formation world dropped under the target
      complement after having reached it (``observe_world``, fed by the
      membership service);
    * **quorum-wait growth** — the last ``agg.quorum_wait_ms`` exceeds
      ``fleet_quorum_factor`` x the trailing median (commits are waiting
      ever longer for quorum: workers dying or slowing);
    * **stalled commit version** — a worker's adopted global version
      (``agg.adopted_version``) stops advancing for
      ``fleet_stalled_pushes`` pushes while its rounds keep completing
      (commit authority dead or unreachable; only armed once a commit
      was ever adopted, so sync runs never match);
    * **partitioned edge** — a worker's per-peer ``wire.errors_total``
      keeps growing across ``fleet_stalled_pushes`` pushes while its
      ``wire.requests_total`` to the SAME peer does not: every exchange
      on that edge is failing, which separates a network partition (the
      worker is alive and pushing telemetry through a different edge)
      from a dead worker (no pushes at all — the straggler/world rules'
      territory).  The alert NAMES the edge: worker, peer, and the
      error count the window accumulated.

    Alert records land in ``<collector dir>/worker_fleet/metrics.jsonl``
    — the same worker-dir layout every fleet reader already consumes, so
    ``fedrec-obs alerts``/``fleet`` render them with no new plumbing.
    """

    _QUORUM_WINDOW = 16
    _QUORUM_MIN_PRIOR = 4

    def __init__(
        self,
        watch_cfg: Any = None,
        *,
        target_world: int = 0,
        registry: MetricsRegistry | None = None,
        tracer: Any = None,
        jsonl_path=None,
    ):
        if watch_cfg is None:
            from fedrec_tpu.config import WatchConfig

            watch_cfg = WatchConfig()
        self.straggler_factor = float(watch_cfg.fleet_straggler_factor)
        self.straggler_evals = max(int(watch_cfg.fleet_straggler_evals), 1)
        self.quorum_factor = float(watch_cfg.fleet_quorum_factor)
        self.stalled_pushes = max(int(watch_cfg.fleet_stalled_pushes), 1)
        self.target_world = int(target_world)
        self.engine = AlertEngine(
            registry=registry,
            tracer=tracer,
            pending_for=1,
            resolve_after=watch_cfg.resolve_after,
            flap_max=watch_cfg.flap_max,
            flap_window=watch_cfg.flap_window,
            history=watch_cfg.history,
            jsonl_path=jsonl_path,
        )
        # per-worker cursors: round-seconds (sum, count), push arrival
        # ts/gap, rounds, version
        self._round_cursor: dict[str, tuple[float, float]] = {}
        self._round_mean: dict[str, float] = {}
        self._push_ts: dict[str, float] = {}
        self._push_gap: dict[str, float] = {}
        self._rounds: dict[str, float] = {}
        self._version: dict[str, float] = {}
        self._version_seen: set[str] = set()
        self._stalled: dict[str, int] = {}
        self._quorum: deque[float] = deque(maxlen=self._QUORUM_WINDOW)
        self._world_was_full = False
        # per-(worker, peer) wire cursors for the partitioned-edge rule
        self._edge_err: dict[tuple[str, str], float] = {}
        self._edge_req: dict[tuple[str, str], float] = {}
        self._edge_stall: dict[tuple[str, str], int] = {}

    # ------------------------------------------------------------- helpers
    @staticmethod
    def _snap_value(snap: dict, name: str) -> float | None:
        from fedrec_tpu.obs.report import snapshot_value

        return snapshot_value(snap, name)

    @staticmethod
    def _edge_totals(snap: dict, name: str) -> dict[str, float]:
        """Per-peer totals of a peer-labelled wire counter (ops summed
        away) out of one snapshot."""
        totals: dict[str, float] = {}
        rows = snap.get("metrics", {}).get(name, {}).get("values", [])
        for row in rows:
            peer = (row.get("labels") or {}).get("peer")
            if peer:
                totals[str(peer)] = (
                    totals.get(str(peer), 0.0) + float(row.get("value", 0.0))
                )
        return totals

    @staticmethod
    def _round_cell(snap: dict) -> tuple[float, float] | None:
        rows = (
            snap.get("metrics", {}).get("train.round_seconds", {})
            .get("values", [])
        )
        for row in rows:
            if not row.get("labels"):
                return float(row.get("sum", 0.0)), float(row.get("count", 0.0))
        return None

    # ------------------------------------------------------------ evaluate
    def observe_world(self, world: int) -> None:
        """Membership-side hook: fire once the formed world drops below
        the target complement it previously reached."""
        if self.target_world <= 0:
            return
        world = int(world)
        if world >= self.target_world:
            self._world_was_full = True
        self.engine.observe(
            "fleet:world_below_target",
            self._world_was_full and world < self.target_world,
            severity="critical",
            summary=(
                f"membership world {world} below target "
                f"{self.target_world}"
            ),
            labels={"world": world, "target": self.target_world},
            value=float(world),
            threshold=float(self.target_world),
        )

    def observe_push(self, worker: str, snapshot: dict | None) -> None:
        """Score one worker's telemetry push against every fleet rule."""
        if not isinstance(snapshot, dict):
            return
        wid = str(worker)
        # ---- persistent straggler: two signatures feed ONE alert key.
        # The round-seconds delta catches a worker whose rounds ARE slow;
        # the push inter-arrival gap catches one that is slow to the
        # wire (an async chaos straggler sleeps at the push boundary —
        # outside its own round timer — but its snapshot timestamps
        # cannot hide the cadence). Each signal compares against the
        # fleet median of the SAME signal.
        cell = self._round_cell(snapshot)
        if cell is not None:
            prev = self._round_cursor.get(wid, (0.0, 0.0))
            self._round_cursor[wid] = cell
            dsum, dcount = cell[0] - prev[0], cell[1] - prev[1]
            if dcount > 0:
                self._round_mean[wid] = dsum / dcount
        ts = snapshot.get("ts")
        if isinstance(ts, (int, float)):
            prev_ts = self._push_ts.get(wid)
            self._push_ts[wid] = float(ts)
            if prev_ts is not None and ts > prev_ts:
                self._push_gap[wid] = float(ts) - prev_ts
        verdicts = []
        for signal, table in (
            ("round", self._round_mean), ("push gap", self._push_gap),
        ):
            mine = table.get(wid)
            if mine is None or len(table) < 2:
                continue
            med = statistics.median(table.values())
            verdicts.append(
                (signal, mine, med,
                 med > 0 and mine > self.straggler_factor * med)
            )
        if verdicts:
            breached = [v for v in verdicts if v[3]]
            signal, mine, med, _ = breached[0] if breached else verdicts[0]
            self.engine.observe(
                f"fleet:straggler:{wid}",
                bool(breached),
                severity="warning",
                summary=(
                    f"persistent straggler: worker {wid} mean {signal} "
                    f"{mine:.2f}s vs fleet median {med:.2f}s "
                    f"(> {self.straggler_factor:g}x)"
                ),
                labels={"worker": wid, "signal": signal},
                value=mine,
                threshold=(
                    self.straggler_factor * med if med > 0 else None
                ),
                pending_for=self.straggler_evals,
            )
        # ---- quorum-wait growth (any worker's agg.quorum_wait_ms gauge)
        qw = self._snap_value(snapshot, "agg.quorum_wait_ms")
        if qw is not None and qw > 0:
            prior = list(self._quorum)
            self._quorum.append(float(qw))
            if len(prior) >= self._QUORUM_MIN_PRIOR:
                med = statistics.median(prior)
                self.engine.observe(
                    "fleet:quorum_wait_growth",
                    med > 0 and qw > self.quorum_factor * med,
                    severity="warning",
                    summary=(
                        f"quorum wait growing: {qw:.0f} ms vs trailing "
                        f"median {med:.0f} ms (> {self.quorum_factor:g}x)"
                    ),
                    value=float(qw),
                    threshold=self.quorum_factor * med if med > 0 else None,
                )
        # ---- stalled commit version: rounds advance, adopted version
        # doesn't (armed only after a first commit was ever adopted)
        rounds = self._snap_value(snapshot, "train.rounds_total")
        version = self._snap_value(snapshot, "agg.adopted_version")
        if rounds is not None and version is not None:
            prev_rounds = self._rounds.get(wid)
            prev_version = self._version.get(wid)
            self._rounds[wid], self._version[wid] = rounds, version
            if version > 0:
                self._version_seen.add(wid)
            if (
                wid in self._version_seen
                and prev_rounds is not None
                and rounds > prev_rounds
                and prev_version is not None
                and version <= prev_version
            ):
                self._stalled[wid] = self._stalled.get(wid, 0) + 1
            else:
                self._stalled[wid] = 0
            if wid in self._version_seen:
                self.engine.observe(
                    f"fleet:stalled_commit:{wid}",
                    self._stalled[wid] >= self.stalled_pushes,
                    severity="critical",
                    summary=(
                        f"stalled commit version: worker {wid} still at "
                        f"global version {version:g} after "
                        f"{self._stalled[wid]} pushes of completed rounds"
                    ),
                    labels={"worker": wid},
                    value=version,
                )
        # ---- partitioned edge: per-peer wire errors grow while requests
        # to the same peer do not — the edge is black-holed, and because
        # this telemetry push itself arrived, the WORKER is alive: a
        # partition, not a death. The alert names the edge.
        errs = self._edge_totals(snapshot, "wire.errors_total")
        if errs:
            reqs = self._edge_totals(snapshot, "wire.requests_total")
            for peer, err_total in errs.items():
                ek = (wid, peer)
                prev_err = self._edge_err.get(ek)
                prev_req = self._edge_req.get(ek, 0.0)
                req_total = reqs.get(peer, 0.0)
                self._edge_err[ek] = err_total
                self._edge_req[ek] = req_total
                if prev_err is None:
                    continue
                if err_total > prev_err and req_total <= prev_req:
                    self._edge_stall[ek] = self._edge_stall.get(ek, 0) + 1
                else:
                    self._edge_stall[ek] = 0
                stalled = self._edge_stall[ek]
                self.engine.observe(
                    f"fleet:partition:{wid}->{peer}",
                    stalled >= self.stalled_pushes,
                    severity="critical",
                    summary=(
                        f"partitioned edge: worker {wid} -> {peer} — wire "
                        f"errors at {err_total:g} and growing with no "
                        f"completed request for {stalled} pushes"
                    ),
                    labels={"worker": wid, "peer": peer},
                    value=err_total,
                )


# ------------------------------------------------------------ record readers
def alert_records(records: list[dict]) -> list[dict]:
    """The ``{"kind": "alert"}`` transition records out of a loaded event
    log, oldest first."""
    out = [r for r in records if r.get("kind") == "alert"]
    out.sort(key=lambda r: r.get("ts", 0.0))
    return out


def active_alerts(records: list[dict]) -> list[dict]:
    """Alerts whose LAST recorded transition is ``firing`` — the active
    set as of the end of the log (the offline twin of
    ``AlertEngine.active``)."""
    last: dict[str, dict] = {}
    for r in alert_records(records):
        key = r.get("key")
        if key:
            last[key] = r
    return [r for r in last.values() if r.get("event") == "firing"]
