"""Process-wide metrics registry: counters, gauges, fixed-bucket histograms.

Before this package the run-time signals were three disjoint ad-hoc
``metrics()`` dicts in serving, a stdout ``MetricLogger``, and nothing at
all from the prefetcher or the DP accountant.  The registry is the one
publication point they all share: every instrument is named, optionally
labeled, thread-safe, and snapshot-able, so a run can be inspected from
a single artifact instead of four incompatible streams.

Design (deliberately Prometheus-client-shaped, but dependency-free):

* **Names are dotted** (``serve.p50_ms``, ``privacy.epsilon_spent``) —
  the internal namespace matches the existing JSONL metric schema.  The
  Prometheus exposition sanitizes them (``serve_p50_ms``) and keeps the
  dotted original in the ``# HELP`` line so operators can grep either.
* **Get-or-create is idempotent**: ``registry.counter("x")`` from two
  modules returns the same instrument; re-registering a name as a
  different kind (or different label names) raises — silent shadowing is
  how metrics go missing.
* **Histograms use fixed upper-bound buckets** with Prometheus ``le``
  semantics (inclusive).  ``quantile()`` gives a linear-interpolation
  estimate for reports; the exact bucket counts ride in every snapshot.
* **Collectors** are callables run just before a snapshot/exposition —
  the hook that lets derived gauges (serve p50/p99, store staleness)
  refresh lazily instead of on every request.

A module-level default registry (``get_registry``) serves production
code; tests swap in a fresh one with ``set_registry`` to assert exact
counts without cross-test bleed.
"""

from __future__ import annotations

import json
import math
import re
import threading
import time
from bisect import bisect_left
from typing import Any, Callable, Mapping, Sequence

# default latency-flavored buckets (ms); callers pass their own for other units
DEFAULT_BUCKETS = (
    0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0, 500.0, 1000.0, 2500.0
)

_PROM_NAME_BAD = re.compile(r"[^a-zA-Z0-9_:]")


def sanitize_prom_name(name: str) -> str:
    """Dotted internal name -> valid Prometheus metric name."""
    out = _PROM_NAME_BAD.sub("_", name)
    if out and out[0].isdigit():
        out = "_" + out
    return out


def _escape_label_value(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


class _Metric:
    """Shared instrument plumbing: per-label-set cells behind one lock."""

    kind = "untyped"

    def __init__(self, name: str, help: str, label_names: Sequence[str]):
        self.name = name
        self.help = help
        self.label_names = tuple(label_names)
        self._lock = threading.Lock()
        self._cells: dict[tuple, Any] = {}

    def _key(self, labels: Mapping[str, Any]) -> tuple:
        if set(labels) != set(self.label_names):
            raise ValueError(
                f"metric {self.name!r} expects labels {self.label_names}, "
                f"got {tuple(labels)}"
            )
        return tuple(str(labels[k]) for k in self.label_names)

    def _label_dict(self, key: tuple) -> dict[str, str]:
        return dict(zip(self.label_names, key))


class Counter(_Metric):
    """Monotonic accumulator.  ``inc`` only; resets happen at process birth."""

    kind = "counter"

    def inc(self, amount: float = 1.0, **labels) -> None:
        if amount < 0:
            raise ValueError(f"counter {self.name!r} cannot decrease")
        key = self._key(labels)
        with self._lock:
            self._cells[key] = self._cells.get(key, 0.0) + amount

    def value(self, **labels) -> float:
        with self._lock:
            return float(self._cells.get(self._key(labels), 0.0))

    def _snapshot_values(self) -> list[dict]:
        with self._lock:
            return [
                {"labels": self._label_dict(k), "value": v}
                for k, v in sorted(self._cells.items())
            ]


class Gauge(_Metric):
    """Point-in-time value; set/inc/dec."""

    kind = "gauge"

    def set(self, value: float, **labels) -> None:
        key = self._key(labels)
        with self._lock:
            self._cells[key] = float(value)

    def inc(self, amount: float = 1.0, **labels) -> None:
        key = self._key(labels)
        with self._lock:
            self._cells[key] = self._cells.get(key, 0.0) + amount

    def dec(self, amount: float = 1.0, **labels) -> None:
        self.inc(-amount, **labels)

    def value(self, **labels) -> float | None:
        with self._lock:
            v = self._cells.get(self._key(labels))
            return None if v is None else float(v)

    def _snapshot_values(self) -> list[dict]:
        with self._lock:
            return [
                {"labels": self._label_dict(k), "value": v}
                for k, v in sorted(self._cells.items())
            ]


class _HistCell:
    __slots__ = ("counts", "sum", "count")

    def __init__(self, n_buckets: int):
        self.counts = [0] * (n_buckets + 1)  # +1 for the implicit +Inf bucket
        self.sum = 0.0
        self.count = 0


class Histogram(_Metric):
    """Fixed-bucket histogram with Prometheus ``le`` (inclusive) semantics.

    ``buckets`` are the finite upper bounds, strictly increasing; the
    ``+Inf`` bucket is implicit.  An observation equal to a bound lands
    in that bound's bucket (``v <= le``).
    """

    kind = "histogram"

    def __init__(
        self,
        name: str,
        help: str,
        label_names: Sequence[str],
        buckets: Sequence[float] = DEFAULT_BUCKETS,
    ):
        super().__init__(name, help, label_names)
        bs = tuple(float(b) for b in buckets)
        if not bs or any(b2 <= b1 for b1, b2 in zip(bs, bs[1:])):
            raise ValueError(
                f"histogram {name!r} buckets must be non-empty and strictly "
                f"increasing, got {bs}"
            )
        if any(math.isinf(b) or math.isnan(b) for b in bs):
            raise ValueError(f"histogram {name!r} buckets must be finite (+Inf is implicit)")
        self.buckets = bs

    def observe(self, value: float, **labels) -> None:
        value = float(value)
        key = self._key(labels)
        # bisect_left on the bounds gives the first bucket with le >= value,
        # which is exactly the inclusive-upper-bound bucket
        idx = bisect_left(self.buckets, value)
        with self._lock:
            cell = self._cells.get(key)
            if cell is None:
                cell = self._cells[key] = _HistCell(len(self.buckets))
            cell.counts[idx] += 1
            cell.sum += value
            cell.count += 1

    def merge_counts(
        self, counts: Sequence[int], sum: float, count: int, **labels
    ) -> None:
        """Bulk-add precomputed per-bucket counts (+Inf bucket LAST, so
        ``len(counts) == len(buckets) + 1``) under ONE lock acquire — the
        vectorized observe path for callers that digest whole arrays at
        once (obs.health publishes a round's (steps × clients) cells per
        call; a Python-level observe() loop there costs milliseconds on
        the round-critical path)."""
        if len(counts) != len(self.buckets) + 1:
            raise ValueError(
                f"histogram {self.name!r} expects {len(self.buckets) + 1} "
                f"bucket counts (+Inf last), got {len(counts)}"
            )
        key = self._key(labels)
        with self._lock:
            cell = self._cells.get(key)
            if cell is None:
                cell = self._cells[key] = _HistCell(len(self.buckets))
            for i, c in enumerate(counts):
                cell.counts[i] += int(c)
            cell.sum += float(sum)
            cell.count += int(count)

    def quantile(self, q: float, **labels) -> float | None:
        """Linear-interpolation estimate of the q-quantile (0 <= q <= 1).
        None before any observation.  Values in the +Inf bucket clamp to
        the largest finite bound (the honest answer a fixed-bucket
        histogram can give).  Delegates to :func:`quantile_from_counts` —
        the ONE estimator, shared with offline report rendering."""
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"quantile must be in [0, 1], got {q}")
        key = self._key(labels)
        with self._lock:
            cell = self._cells.get(key)
            if cell is None or cell.count == 0:
                return None
            counts = list(cell.counts)
        return quantile_from_counts(q, self.buckets, counts)

    def cell(self, **labels) -> dict | None:
        key = self._key(labels)
        with self._lock:
            c = self._cells.get(key)
            if c is None:
                return None
            return {"sum": c.sum, "count": c.count, "counts": list(c.counts)}

    def _snapshot_values(self) -> list[dict]:
        with self._lock:
            out = []
            for k, c in sorted(self._cells.items()):
                out.append({
                    "labels": self._label_dict(k),
                    "sum": c.sum,
                    "count": c.count,
                    "buckets": {
                        ("+Inf" if i == len(self.buckets) else repr(self.buckets[i])): n
                        for i, n in enumerate(c.counts)
                    },
                })
            return out


class MetricsRegistry:
    """Named instruments + collectors; the process's one metrics namespace."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: dict[str, _Metric] = {}
        self._collectors: list[Callable[[], None]] = []
        self.created_at = time.time()
        # fleet correlation keys (obs.fleet.set_fleet_identity): stamped
        # into every snapshot as its "fleet" key and merged into each
        # MetricLogger JSONL record, so artifacts from different
        # processes are joinable offline
        self._context: dict[str, Any] = {}

    def set_context(self, **kv: Any) -> None:
        """Replace the fleet label set carried by subsequent snapshots."""
        with self._lock:
            self._context = {k: v for k, v in kv.items() if v is not None}

    @property
    def context(self) -> dict[str, Any]:
        with self._lock:
            return dict(self._context)

    # ------------------------------------------------------- instruments
    def _get_or_create(self, cls, name, help, labels, **kw) -> _Metric:
        with self._lock:
            m = self._metrics.get(name)
            if m is not None:
                if not isinstance(m, cls) or m.label_names != tuple(labels):
                    raise ValueError(
                        f"metric {name!r} already registered as {m.kind} with "
                        f"labels {m.label_names}; cannot re-register as "
                        f"{cls.kind} with labels {tuple(labels)}"
                    )
                want_buckets = kw.get("buckets")
                if want_buckets is not None and m.buckets != tuple(
                    float(b) for b in want_buckets
                ):
                    # buckets are part of a histogram's identity: observations
                    # silently landing in someone else's bucket layout is the
                    # exact shadowing this registry promises to reject
                    raise ValueError(
                        f"histogram {name!r} already registered with buckets "
                        f"{m.buckets}; cannot re-register with {tuple(want_buckets)}"
                    )
                return m
            m = cls(name, help, labels, **kw)
            self._metrics[name] = m
            return m

    def counter(self, name: str, help: str = "", labels: Sequence[str] = ()) -> Counter:
        return self._get_or_create(Counter, name, help, labels)

    def gauge(self, name: str, help: str = "", labels: Sequence[str] = ()) -> Gauge:
        return self._get_or_create(Gauge, name, help, labels)

    def histogram(
        self,
        name: str,
        help: str = "",
        labels: Sequence[str] = (),
        buckets: Sequence[float] = DEFAULT_BUCKETS,
    ) -> Histogram:
        return self._get_or_create(Histogram, name, help, labels, buckets=buckets)

    def get(self, name: str) -> _Metric | None:
        with self._lock:
            return self._metrics.get(name)

    def names(self) -> list[str]:
        with self._lock:
            return sorted(self._metrics)

    # ------------------------------------------------------- collectors
    def register_collector(self, fn: Callable[[], None]) -> None:
        """``fn`` runs (best-effort) before every snapshot/exposition —
        the refresh hook for derived gauges."""
        with self._lock:
            if fn not in self._collectors:
                self._collectors.append(fn)

    def unregister_collector(self, fn: Callable[[], None]) -> None:
        with self._lock:
            if fn in self._collectors:
                self._collectors.remove(fn)

    def _run_collectors(self) -> None:
        with self._lock:
            collectors = list(self._collectors)
        for fn in collectors:
            try:
                fn()
            except Exception:  # noqa: BLE001 — telemetry must never take down the host
                pass

    # --------------------------------------------------------- snapshots
    def snapshot(self) -> dict:
        """One JSON-serializable dict of every instrument's current state."""
        self._run_collectors()
        with self._lock:
            metrics = list(self._metrics.items())
            context = dict(self._context)
        return {
            "kind": "registry_snapshot",
            "ts": time.time(),
            **({"fleet": context} if context else {}),
            "metrics": {
                name: {
                    "kind": m.kind,
                    "help": m.help,
                    "values": m._snapshot_values(),
                }
                for name, m in sorted(metrics)
            },
        }

    def write_snapshot(self, path) -> dict:
        """Append one snapshot line to a JSONL event log; returns the snapshot."""
        snap = self.snapshot()
        with open(path, "a") as f:
            f.write(json.dumps(snap) + "\n")
            f.flush()
        return snap

    # -------------------------------------------------------- prometheus
    def to_prometheus(self) -> str:
        """Text exposition format (version 0.0.4) — the shared
        :func:`snapshot_to_prometheus` over a fresh snapshot, so the live
        endpoint and the offline ``fedrec-obs prom`` twin can never
        drift."""
        return snapshot_to_prometheus(self.snapshot())


def _fmt_labels(labels: Mapping[str, Any]) -> str:
    if not labels:
        return ""
    inner = ",".join(
        f'{sanitize_prom_name(str(k))}="{_escape_label_value(str(v))}"'
        for k, v in sorted(labels.items())
    )
    return "{" + inner + "}"


def _fmt_num(v: float) -> str:
    f = float(v)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def quantile_from_counts(
    q: float, bounds: Sequence[float], counts: Sequence[float]
) -> float | None:
    """Linear-interpolation quantile over histogram buckets.

    ``bounds``: ascending finite upper bounds; ``counts``: per-bucket
    counts with the +Inf bucket LAST (``len(counts) == len(bounds) + 1``).
    THE estimator — ``Histogram.quantile`` runs it over a live cell and
    ``fedrec_tpu.obs.report.histogram_quantile`` over an exported row, so
    live and offline percentiles can never drift.
    """
    total = sum(counts)
    if total == 0 or not bounds:
        return None
    rank = q * total
    cum = 0.0
    for i, c in enumerate(counts):
        prev = cum
        cum += c
        if cum >= rank and c > 0:
            if i >= len(bounds):
                return bounds[-1]  # +Inf bucket: clamp to the last finite bound
            lo = bounds[i - 1] if i > 0 else 0.0
            frac = (rank - prev) / c
            return lo + (bounds[i] - lo) * min(max(frac, 0.0), 1.0)
    return bounds[-1]


def snapshot_to_prometheus(snap: dict) -> str:
    """Render an (exported or live) registry snapshot dict as Prometheus
    text.  Dotted internal names are sanitized; the HELP line carries the
    dotted original so both spellings are greppable.  THE renderer —
    ``MetricsRegistry.to_prometheus`` and the ``fedrec-obs prom`` CLI both
    call it, so label escaping and number formatting stay byte-identical
    online and offline."""
    lines: list[str] = []
    for name, m in sorted(snap.get("metrics", {}).items()):
        pname = sanitize_prom_name(name)
        help_text = name + (f" — {m['help']}" if m.get("help") else "")
        lines.append(f"# HELP {pname} {help_text}")
        lines.append(f"# TYPE {pname} {m.get('kind', 'untyped')}")
        for row in m.get("values", []):
            labels = row.get("labels", {})
            label_str = _fmt_labels(labels)
            if "buckets" in row:
                cum = 0
                for le, n in row["buckets"].items():
                    cum += n
                    le_val = le if le == "+Inf" else repr(float(le))
                    bl = _fmt_labels({**labels, "le": le_val})
                    lines.append(f"{pname}_bucket{bl} {cum}")
                lines.append(f"{pname}_sum{label_str} {_fmt_num(row['sum'])}")
                lines.append(f"{pname}_count{label_str} {int(row['count'])}")
            else:
                lines.append(f"{pname}{label_str} {_fmt_num(row['value'])}")
    return "\n".join(lines) + ("\n" if lines else "")


# ------------------------------------------------------------- global default
_default_registry = MetricsRegistry()
_default_lock = threading.Lock()


def get_registry() -> MetricsRegistry:
    """The process-wide default registry every subsystem publishes into."""
    return _default_registry


def set_registry(registry: MetricsRegistry) -> MetricsRegistry:
    """Swap the default registry (tests); returns the previous one."""
    global _default_registry
    with _default_lock:
        prev = _default_registry
        _default_registry = registry
        return prev
