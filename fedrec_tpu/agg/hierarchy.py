"""Hierarchical (tiered) robust reduction — `agg.mode` = "hierarchical".

The flat reduce applies ``fed.robust`` once over all P contributions;
here the P contributions are grouped into ``agg.tree_fanout``-wide tiers,
each tier is pre-aggregated with the SAME robust method, and the tier
outputs are reduced up the tree.  Tier groups at one level are
independent, so on a real deployment they run on distinct hosts in
parallel and the round's reduce cost is the per-level MAX group time
summed over the O(log_fanout P) levels — not the O(P) flat sweep
(:func:`tree_critical_path_ms` is that accounting, and
``benchmarks/agg_scale.py`` banks the measured frontier).

Two semantic regimes, pinned in ``tests/test_agg.py``:

  * ``method == "mean"`` — each tier carries (sum(w*x), sum(w)) partial
    sums and ONE divide happens at the root.  A tree of partial sums is
    *algebraically* the flat weighted mean, so the Trainer never routes
    mean through this module at all: hierarchical+mean lowers to the
    unchanged flat collective and is bit-identical by construction
    (float summation ORDER is the implementation's right; the partial
    sums here are f64, matching :func:`robust_reduce_np`'s mean).
  * any other method — trimming/median/clip act on tier PRE-AGGREGATES
    above the leaf level, not on raw cohort members, so the trajectory
    genuinely diverges from the flat robust reduce (a tier of honest
    clients can absorb a poisoned member before the next tier sees it).
    The divergence is bounded-delta pinned and documented in
    docs/DESIGN.md.

Topology is rebuilt from the CURRENT member count on every call
(:func:`build_tree` is deterministic in (count, fanout)), so when a
membership epoch shrinks or a peer rejoins the tree reforms with the
new world — there is no cached topology to invalidate.
"""

from __future__ import annotations

import time
from typing import Any

import jax
import numpy as np

from fedrec_tpu.fed.robust import robust_reduce_tree_np, validate_robust_method

__all__ = ["build_tree", "tree_critical_path_ms", "tree_reduce_np"]


def build_tree(count: int, fanout: int) -> list[list[list[int]]]:
    """Deterministic reduce-tree plan over ``count`` rank-ordered members.

    Returns one list of groups per level; each group is a list of indices
    into the PREVIOUS level's outputs (level 0 indexes the raw members).
    Contiguous rank-order grouping keeps co-located ranks (same host's
    processes are adjacent ranks) in the same tier, which is what makes
    the per-host pre-aggregate local.  ``count`` <= ``fanout`` is the
    degenerate single-group tree — one level, identical to flat.
    """
    if count < 1:
        raise ValueError(f"reduce tree needs >= 1 member, got {count}")
    if fanout < 2:
        raise ValueError(f"agg.tree_fanout must be >= 2, got {fanout}")
    levels: list[list[list[int]]] = []
    cur = count
    while cur > 1:
        groups = [
            list(range(i, min(i + fanout, cur))) for i in range(0, cur, fanout)
        ]
        levels.append(groups)
        cur = len(groups)
    if not levels:  # count == 1: a single trivial level keeps callers uniform
        levels.append([[0]])
    return levels


def tree_critical_path_ms(stats: dict) -> float:
    """The parallel-deployment cost of a measured reduce: per level the
    groups run concurrently on distinct hosts, so the level costs its
    slowest group and the tree costs the sum of levels."""
    return float(sum(lv["max_group_ms"] for lv in stats.get("levels", [])))


def tree_reduce_np(
    gathered_tree: Any,
    weights: np.ndarray,
    fanout: int,
    method: str,
    trim_k: int = 1,
    clip_norm: float = 10.0,
    fallback_tree: Any = None,
    stats: dict | None = None,
) -> Any:
    """Tiered numpy robust reduction: every leaf of ``gathered_tree`` is a
    (P, ...) stack; the P contributions reduce up a
    :func:`build_tree`-planned tree, each group via the SAME
    ``fed.robust`` reducer the flat path uses
    (:func:`~fedrec_tpu.fed.robust.robust_reduce_tree_np`), so robust
    semantics compose per tier rather than being reimplemented here.

    A tier output's weight at the next level is its group's summed
    weight: for "mean" this makes the tree algebraically the flat
    weighted mean (pinned), for robust methods it keeps participation
    (weight > 0) flowing upward.  An all-zero-weight group contributes
    weight 0 and its (fallback) value is masked out one level up —
    matching the flat reduce's treatment of non-participants.

    ``stats`` (out-param) records per-level group counts and timings;
    :func:`tree_critical_path_ms` turns them into the parallel cost.
    """
    validate_robust_method(method)
    leaves, treedef = jax.tree_util.tree_flatten(gathered_tree)
    stacks = [np.asarray(leaf, np.float64) for leaf in leaves]
    count = stacks[0].shape[0]
    w = np.asarray(weights, np.float64)
    if w.shape[0] != count:
        raise ValueError(f"weights {w.shape} do not match stack P={count}")
    fb_leaves: list = [None] * len(stacks)
    if fallback_tree is not None:
        fb_leaves = jax.tree_util.tree_flatten(fallback_tree)[0]
    if stats is not None:
        stats.setdefault("levels", [])
        stats["members"] = int(count)
        stats["fanout"] = int(fanout)

    if method == "mean":
        out = _mean_tree(stacks, w, fanout, stats)
        return jax.tree_util.tree_unflatten(treedef, out)

    for groups in build_tree(count, fanout):
        next_stacks: list[list[np.ndarray]] = [[] for _ in stacks]
        next_w = np.zeros((len(groups),), np.float64)
        group_ms: list[float] = []
        for gi, idxs in enumerate(groups):
            t0 = time.monotonic()
            sub_w = w[idxs]
            next_w[gi] = float(np.sum(sub_w * (sub_w > 0)))
            if next_w[gi] == 0.0:
                # no participant in the tier: carry the fallback (masked
                # out by weight 0 at the next level)
                for li, fb in enumerate(fb_leaves):
                    cell = (
                        np.asarray(fb, np.float64)
                        if fb is not None
                        else np.zeros(stacks[li].shape[1:], np.float64)
                    )
                    next_stacks[li].append(cell)
                group_ms.append((time.monotonic() - t0) * 1e3)
                continue
            sub_tree = jax.tree_util.tree_unflatten(
                treedef, [s[idxs] for s in stacks]
            )
            reduced = robust_reduce_tree_np(
                sub_tree,
                sub_w,
                method,
                trim_k=trim_k,
                clip_norm=clip_norm,
                fallback_tree=fallback_tree,
            )
            for li, leaf in enumerate(jax.tree_util.tree_flatten(reduced)[0]):
                next_stacks[li].append(np.asarray(leaf, np.float64))
            group_ms.append((time.monotonic() - t0) * 1e3)
        stacks = [np.stack(cells, axis=0) for cells in next_stacks]
        w = next_w
        if stats is not None:
            stats["levels"].append(
                {
                    "groups": len(groups),
                    "max_group_ms": max(group_ms) if group_ms else 0.0,
                    "total_ms": float(sum(group_ms)),
                }
            )
    out = [s[0] for s in stacks]
    return jax.tree_util.tree_unflatten(treedef, out)


def _mean_tree(
    stacks: list[np.ndarray], w: np.ndarray, fanout: int, stats: dict | None
) -> list[np.ndarray]:
    """The partial-sum lowering: tiers carry (sum(w*x), sum(w)) and the
    ONE divide happens at the root — algebraically the flat weighted
    mean (``tests/test_agg.py`` pins exactness on binary-representable
    data and allclose in general)."""
    total = float(np.sum(w * (w > 0)))
    if total == 0:
        raise ValueError("mean reduction needs >= 1 participant")
    wmask = w > 0
    partials = [
        np.einsum(
            "p,p...->p...", w * wmask, np.where(
                wmask.reshape((-1,) + (1,) * (s.ndim - 1)), s, 0.0
            )
        )
        for s in stacks
    ]
    count = partials[0].shape[0]
    for groups in build_tree(count, fanout):
        group_ms: list[float] = []
        next_partials: list[list[np.ndarray]] = [[] for _ in partials]
        for idxs in groups:
            t0 = time.monotonic()
            for li, p in enumerate(partials):
                next_partials[li].append(p[idxs].sum(axis=0))
            group_ms.append((time.monotonic() - t0) * 1e3)
        partials = [np.stack(cells, axis=0) for cells in next_partials]
        if stats is not None:
            stats["levels"].append(
                {
                    "groups": len(groups),
                    "max_group_ms": max(group_ms) if group_ms else 0.0,
                    "total_ms": float(sum(group_ms)),
                }
            )
    return [p[0] / total for p in partials]
