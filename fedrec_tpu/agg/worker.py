"""Async worker loop — one process's side of the buffered-commit wire.

An async worker is a SINGLE-process Trainer (no collective world — the
all-process barrier is exactly what async mode removes) that, per
round:

  1. trains its local round (``Trainer.train_round_recovering``),
  2. computes its contribution DELTA against the global version it
     trained from,
  3. pushes the delta to the :mod:`~fedrec_tpu.agg.server` commit
     authority (after the scripted chaos delay, when this worker is the
     smoke's straggler — ``chaos.straggle_ms`` is the host-driven
     straggle knob and sleeps here, at the push boundary).  With
     ``fed.dcn_compress`` set, the push ships ENCODED per-leaf payloads
     instead of dense leaves: linear sketches go up raw (the server
     folds them in sketch space), per-contribution codecs go up with
     this worker's locally-held error-feedback residual already folded
     in — the residual lives at the encoding edge, banked against the
     version the push was based on, and what the encode drops this
     round rides the next round's delta,
  4. polls for a NEWER committed global (bounded wait — on timeout the
     worker proceeds from its own params and its next push simply
     carries higher staleness; that is the async contract, not an
     error) and adopts it via ``set_global_params``.

Because every worker seeds identically (same config, same
``train.seed``), the first worker's ``init`` push IS the version-0
global; the others verify against it by adopting it.
"""

from __future__ import annotations

import time

import jax
import numpy as np

__all__ = ["run_async_worker"]


def _flatten_params(trainer) -> tuple[list[np.ndarray], object]:
    user_params, news_params = trainer._client0_params()
    leaves, treedef = jax.tree_util.tree_flatten((user_params, news_params))
    return [np.asarray(x) for x in leaves], treedef


def run_async_worker(
    trainer,
    server: str,
    worker_id: str,
    timeout_s: float = 60.0,
    poll_s: float = 0.2,
    global_wait_s: float = 20.0,
) -> list:
    """Drive ``trainer`` for its configured rounds against the commit
    authority at ``server`` ("HOST:PORT").  Returns the round history
    (same shape as ``Trainer.run``)."""
    from fedrec_tpu.agg.server import (
        decode_leaves,
        encode_leaves,
        encode_payloads,
    )
    from fedrec_tpu.comms import (
        codec_caps,
        decode_leaf,
        encode_leaf,
        payload_nbytes,
        validate_codec,
    )
    from fedrec_tpu.obs import wire
    from fedrec_tpu.obs.fleet import request_json_line

    cfg = trainer.cfg
    host, port_s = server.rsplit(":", 1)
    port = int(port_s)
    codec = cfg.fed.dcn_compress
    if codec != "none":
        # "auto" never reaches here (the trainer guard pins async to
        # concrete codecs); a bad name fails before any training
        validate_codec(codec)
    use_ef = (
        codec != "none"
        and codec_caps(codec).supports_error_feedback
        and cfg.fed.dcn_error_feedback
    )
    ef_residual: list | None = None   # this edge's banked encode error

    def rpc(req: dict) -> dict:
        return request_json_line(host, port, req, timeout_s=timeout_s)

    g_version = trainer.registry.gauge(
        "agg.global_version",
        "committed global version this worker last adopted",
    )
    g_staleness = trainer.registry.gauge(
        "agg.staleness",
        "commits the global had advanced past this worker's base when it "
        "pushed (worker-side view)",
    )
    c_pushes = trainer.registry.counter(
        "agg.pushes_total", "contribution deltas this worker pushed"
    )
    c_uplink = trainer.registry.counter(
        "agg.uplink_bytes_total",
        "encoded contribution bytes this worker pushed (measured payload "
        "buffers, pre-base64) — the async uplink the codec compresses",
    )

    epoch = 0
    hello = rpc({"cmd": "hello", "worker": worker_id, "epoch": epoch})
    version = int(hello["version"])
    leaves, treedef = _flatten_params(trainer)
    if not hello.get("have_global"):
        rpc({
            "cmd": "init", "worker": worker_id,
            "payload": encode_leaves(leaves),
        })
    resp = rpc({"cmd": "global", "since": -1})
    if "payload" in resp:
        base = decode_leaves(resp["payload"])
        version = int(resp["version"])
        _adopt(trainer, treedef, base)
    else:
        base = leaves

    straggle_s = (
        cfg.chaos.straggle_ms / 1e3
        if cfg.chaos.enabled and cfg.chaos.straggle_ms > 0
        else 0.0
    )
    history = []
    for round_idx in range(trainer.start_round, cfg.fed.rounds):
        # train_round_recovering already commits the population schedule
        # and ticks quarantine; _after_round is the run()-loop half
        # (logging, cadence snapshots, fleet push) we replicate here
        result = trainer.train_round_recovering(round_idx)
        history.append(result)
        trainer._after_round(result)

        after, _ = _flatten_params(trainer)
        delta = [a - b for a, b in zip(after, base)]
        if codec == "none":
            wire_payload = encode_leaves(delta)
            c_uplink.inc(float(sum(np.asarray(d).nbytes for d in delta)))
        else:
            # the error-feedback residual lives HERE, at the encoding
            # edge: fold last round's dropped mass into this round's
            # delta before encoding, bank what this encode drops
            acc = (
                [d + r for d, r in zip(delta, ef_residual)]
                if use_ef and ef_residual is not None
                else delta
            )
            payloads = [
                encode_leaf(
                    a, codec, cfg.fed.dcn_topk_ratio,
                    sketch_width=cfg.fed.dcn_sketch_width,
                    sketch_seed=cfg.fed.dcn_sketch_seed, leaf_id=j,
                )
                for j, a in enumerate(acc)
            ]
            if use_ef:
                ef_residual = [
                    a - decode_leaf(p, codec, a.shape, leaf_id=j)
                    for j, (a, p) in enumerate(zip(acc, payloads))
                ]
            wire_payload = encode_payloads(payloads)
            c_uplink.inc(float(sum(payload_nbytes(p) for p in payloads)))
        if straggle_s > 0:
            print(
                f"[agg-worker {worker_id}] straggling "
                f"{straggle_s:.1f}s before the round-{round_idx} push",
                flush=True,
            )
            time.sleep(straggle_s)
        with trainer.tracer.span("agg.push", round=round_idx,
                                 based_on=version):
            resp = rpc({
                "cmd": "push", "worker": worker_id, "round": round_idx,
                "epoch": epoch, "based_on": version, "weight": 1.0,
                "payload": wire_payload, "codec": codec,
            })
        c_pushes.inc()
        g_staleness.set(float(max(0, int(resp["version"]) - version)))

        # bounded wait for a commit NEWER than our base; timing out is
        # the async contract (train on, push staler next round)
        deadline = time.monotonic() + global_wait_s
        new_version, payload, commit_flow = version, None, None
        while time.monotonic() < deadline:
            resp = rpc({"cmd": "global", "since": version})
            if "payload" in resp:
                new_version, payload = int(resp["version"]), resp["payload"]
                # the commit's flow id rides the reply ENVELOPE: finish
                # the server's commit arrow inside our adoption span
                reply_env = wire.last_reply_envelope()
                if reply_env is not None:
                    commit_flow = reply_env.get("commit_flow")
                break
            time.sleep(poll_s)
        if payload is not None:
            with trainer.tracer.span("agg.adopt", version=new_version,
                                     round=round_idx):
                if commit_flow is not None:
                    trainer.tracer.flow("in", int(commit_flow))
                base = decode_leaves(payload)
                version = new_version
                _adopt(trainer, treedef, base)
            g_version.set(float(version))
        else:
            base = after
            print(
                f"[agg-worker {worker_id}] no commit within "
                f"{global_wait_s:.0f}s after round {round_idx}; "
                "proceeding stale",
                flush=True,
            )

    # the run()-loop's exit-path bookkeeping: artifacts + final push
    if trainer._obs_dir is not None:
        try:
            from fedrec_tpu.obs import dump_artifacts

            dump_artifacts(
                trainer._obs_dir, registry=trainer.registry,
                tracer=trainer.tracer,
            )
        except OSError as e:
            print(f"[agg-worker {worker_id}] could not write obs "
                  f"artifacts: {e}", flush=True)
    if trainer.fleet_pusher is not None:
        trainer.fleet_pusher.push(final=True)
    try:
        trainer.logger.finish()
    except Exception as e:  # noqa: BLE001 — a flush error must not fail the run
        print(f"[agg-worker {worker_id}] logger.finish failed: {e}",
              flush=True)
    return history


def _adopt(trainer, treedef, leaves: list[np.ndarray]) -> None:
    user_params, news_params = jax.tree_util.tree_unflatten(treedef, leaves)
    trainer.set_global_params(user_params, news_params)
