"""Async worker loop — one process's side of the buffered-commit wire.

An async worker is a SINGLE-process Trainer (no collective world — the
all-process barrier is exactly what async mode removes) that, per
round:

  1. trains its local round (``Trainer.train_round_recovering``),
  2. computes its contribution DELTA against the global version it
     trained from,
  3. pushes the delta to the :mod:`~fedrec_tpu.agg.server` commit
     authority (after the scripted chaos delay, when this worker is the
     smoke's straggler — ``chaos.straggle_ms`` is the host-driven
     straggle knob and sleeps here, at the push boundary).  With
     ``fed.dcn_compress`` set, the push ships ENCODED per-leaf payloads
     instead of dense leaves: linear sketches go up raw (the server
     folds them in sketch space), per-contribution codecs go up with
     this worker's locally-held error-feedback residual already folded
     in — the residual lives at the encoding edge, banked against the
     version the push was based on, and what the encode drops this
     round rides the next round's delta,
  4. polls for a NEWER committed global (bounded wait — on timeout the
     worker proceeds from its own params and its next push simply
     carries higher staleness; that is the async contract, not an
     error) and adopts it via ``set_global_params``.

Because every worker seeds identically (same config, same
``train.seed``), the first worker's ``init`` push IS the version-0
global; the others verify against it by adopting it.

Partition tolerance (ROADMAP 1(c)) rides
:class:`fedrec_tpu.parallel.rpc.FleetRpc`: every exchange retries
transport failures inside the ``agg.worker_*`` budgets with full-jitter
backoff and a per-edge circuit breaker.  When the authority stays
unreachable the worker DEGRADES instead of crashing — each contribution
it cannot deliver parks on an unacked list (its client-generated
``push_id`` is reused verbatim on the retry, so the authority's ledger
can never fold it twice) and training continues, until the wire has
been silent longer than ``agg.worker_unreachable_budget_s``; then it
raises :class:`~fedrec_tpu.parallel.rpc.AuthorityUnreachable` and the
CLI exits rc-75 for the supervisor.  When the authority RESTARTS the
worker notices the incarnation bump in any reply, re-hellos, flushes
the unacked backlog, and adopts the restored committed global
(``agg.resyncs_total`` counts these) — acked history is never
re-trained, and a push the restore left behind ("rebase" error reply:
its base is ahead of the restored global) is dropped in favor of
adopting the authority's current truth.
"""

from __future__ import annotations

import time
import zlib

import jax
import numpy as np

__all__ = ["run_async_worker"]


def _flatten_params(trainer) -> tuple[list[np.ndarray], object]:
    user_params, news_params = trainer._client0_params()
    leaves, treedef = jax.tree_util.tree_flatten((user_params, news_params))
    return [np.asarray(x) for x in leaves], treedef


def run_async_worker(
    trainer,
    server: str,
    worker_id: str,
    timeout_s: float | None = None,
    poll_s: float | None = None,
    global_wait_s: float | None = None,
) -> list:
    """Drive ``trainer`` for its configured rounds against the commit
    authority at ``server`` ("HOST:PORT").  Returns the round history
    (same shape as ``Trainer.run``).  The keyword knobs default to the
    ``agg.worker_*`` config values; explicit arguments win (tests pin
    tight deadlines without a config round-trip).  Raises
    :class:`~fedrec_tpu.parallel.rpc.AuthorityUnreachable` when the
    authority stays dark past ``agg.worker_unreachable_budget_s``."""
    from fedrec_tpu.agg.server import (
        decode_leaves,
        encode_leaves,
        encode_payloads,
    )
    from fedrec_tpu.comms import (
        codec_caps,
        decode_leaf,
        encode_leaf,
        payload_nbytes,
        validate_codec,
    )
    from fedrec_tpu.obs import wire
    from fedrec_tpu.parallel.rpc import (
        AuthorityUnreachable,
        FleetRpc,
        RpcPolicy,
        new_push_id,
    )

    cfg = trainer.cfg
    host, port_s = server.rsplit(":", 1)
    port = int(port_s)
    if timeout_s is None:
        timeout_s = float(cfg.agg.worker_timeout_s)
    if poll_s is None:
        poll_s = float(cfg.agg.worker_poll_s)
    if global_wait_s is None:
        global_wait_s = float(cfg.agg.worker_global_wait_s)
    unreachable_budget_s = float(cfg.agg.worker_unreachable_budget_s)
    codec = cfg.fed.dcn_compress
    if codec != "none":
        # "auto" never reaches here (the trainer guard pins async to
        # concrete codecs); a bad name fails before any training
        validate_codec(codec)
    use_ef = (
        codec != "none"
        and codec_caps(codec).supports_error_feedback
        and cfg.fed.dcn_error_feedback
    )
    ef_residual: list | None = None   # this edge's banked encode error

    rpc = FleetRpc(host, port, RpcPolicy(
        connect_timeout_s=cfg.agg.worker_connect_timeout_s,
        read_timeout_s=timeout_s,
        attempts=cfg.agg.worker_rpc_attempts,
        backoff_base_ms=cfg.agg.worker_backoff_ms,
        backoff_max_ms=cfg.agg.worker_backoff_cap_ms,
        # the bounded poll loop IS the retry for `global`; re-dialing
        # inside one poll tick would double-spend the wait budget
        op_attempts={"global": 1},
        # probe a dead authority at least about once per round: an open
        # breaker makes the round loop fail fast, so the reset window is
        # what paces recovery detection — cap it at the per-round wait
        breaker_reset_s=min(10.0, global_wait_s),
        # decorrelate the fleet's jitter streams without per-worker config
        seed=zlib.crc32(worker_id.encode()),
    ))

    g_version = trainer.registry.gauge(
        "agg.global_version",
        "committed global version this worker last adopted",
    )
    g_staleness = trainer.registry.gauge(
        "agg.staleness",
        "commits the global had advanced past this worker's base when it "
        "pushed (worker-side view)",
    )
    c_pushes = trainer.registry.counter(
        "agg.pushes_total", "contribution deltas this worker pushed"
    )
    c_uplink = trainer.registry.counter(
        "agg.uplink_bytes_total",
        "encoded contribution bytes this worker pushed (measured payload "
        "buffers, pre-base64) — the async uplink the codec compresses",
    )
    c_resyncs = trainer.registry.counter(
        "agg.resyncs_total",
        "re-hello/re-adopt cycles after an authority incarnation bump or "
        "rebase reply (the crash-recovery handshake; 0 when the authority "
        "never restarted)",
    )

    epoch = 0
    incarnation: int | None = None
    # contributions the wire failed to deliver: each req keeps its
    # push_id, so the eventual retry is idempotent at the authority
    unacked: list[dict] = []
    version = 0
    base: list[np.ndarray] = []
    treedef = None

    def note_incarnation(resp: dict) -> bool:
        """Adopt the authority's advertised incarnation; True when it
        BUMPED (the authority restarted since our last exchange)."""
        nonlocal incarnation
        adv = resp.get("incarnation")
        if adv is None:
            return False
        adv = int(adv)
        bumped = incarnation is not None and adv != incarnation
        incarnation = adv
        return bumped

    def check_budget(cause: Exception | None = None) -> None:
        silent = rpc.unreachable_for()
        if silent > unreachable_budget_s:
            raise AuthorityUnreachable(
                f"commit authority {rpc.peer} unreachable for "
                f"{silent:.0f}s (budget agg.worker_unreachable_budget_s="
                f"{unreachable_budget_s:g}s, {len(unacked)} unacked "
                "pushes parked) — exiting rc-75 for the supervisor"
            ) from cause

    def flush_unacked() -> bool:
        """Re-deliver parked pushes in arrival order; stops at the first
        transport failure (the wire is still down — keep them parked).
        True when any reply advertised a BUMPED incarnation (the
        authority restarted: the round loop should resync; the resync
        path itself ignores the return — it is already the handshake)."""
        bumped = False
        while unacked:
            req = unacked[0]
            try:
                resp = rpc.call(req, op="push")
            except OSError as e:
                check_budget(e)
                return bumped
            except ValueError:
                # the authority answered and refused (restored global is
                # behind this push's base, or the entry can no longer
                # fold) — this contribution is unfoldable, drop it
                print(
                    f"[agg-worker {worker_id}] dropping unacked push "
                    f"{req.get('push_id', '?')} (authority refused it "
                    "after restart)",
                    flush=True,
                )
                unacked.pop(0)
                continue
            unacked.pop(0)
            bumped = note_incarnation(resp) or bumped
            if resp.get("duplicate"):
                print(
                    f"[agg-worker {worker_id}] push "
                    f"{req.get('push_id', '?')} was already folded "
                    "(idempotent retry)",
                    flush=True,
                )
        return bumped

    def resync(reason: str) -> bool:
        """The crash-recovery handshake: re-hello, flush the unacked
        backlog, adopt the authority's current committed global.  True
        when a global was adopted (the round loop must not clobber
        ``base`` afterwards).  Best-effort on a dead wire — the degrade
        budget is the backstop."""
        nonlocal version, base
        c_resyncs.inc()
        print(
            f"[agg-worker {worker_id}] resyncing with {rpc.peer} "
            f"({reason})",
            flush=True,
        )
        try:
            hello = rpc.call(
                {"cmd": "hello", "worker": worker_id, "epoch": epoch},
                op="hello",
            )
            note_incarnation(hello)
            flush_unacked()
            resp = rpc.call({"cmd": "global", "since": -1}, op="global")
        except OSError as e:
            check_budget(e)
            return False
        note_incarnation(resp)
        if "payload" in resp:
            base = decode_leaves(resp["payload"])
            version = int(resp["version"])
            _adopt(trainer, treedef, base)
            g_version.set(float(version))
            return True
        return False

    # ----------------------------------------------------------- bootstrap
    # without a hello + a version-0 global there is nothing to train
    # against, so bootstrap failures are immediately rc-75 material — the
    # supervisor respawns us against a (re)started authority
    try:
        hello = rpc.call(
            {"cmd": "hello", "worker": worker_id, "epoch": epoch}, op="hello"
        )
        note_incarnation(hello)
        version = int(hello["version"])
        leaves, treedef = _flatten_params(trainer)
        if not hello.get("have_global"):
            rpc.call({
                "cmd": "init", "worker": worker_id,
                "payload": encode_leaves(leaves),
            }, op="init")
        resp = rpc.call({"cmd": "global", "since": -1}, op="global")
    except OSError as e:
        raise AuthorityUnreachable(
            f"commit authority {rpc.peer} unreachable during bootstrap "
            f"({e}) — exiting rc-75 for the supervisor"
        ) from e
    note_incarnation(resp)
    if "payload" in resp:
        base = decode_leaves(resp["payload"])
        version = int(resp["version"])
        _adopt(trainer, treedef, base)
    else:
        base = leaves

    straggle_s = (
        cfg.chaos.straggle_ms / 1e3
        if cfg.chaos.enabled and cfg.chaos.straggle_ms > 0
        else 0.0
    )
    history = []
    for round_idx in range(trainer.start_round, cfg.fed.rounds):
        # train_round_recovering already commits the population schedule
        # and ticks quarantine; _after_round is the run()-loop half
        # (logging, cadence snapshots, fleet push) we replicate here
        result = trainer.train_round_recovering(round_idx)
        history.append(result)
        trainer._after_round(result)

        adopted_this_round = False
        after, _ = _flatten_params(trainer)
        delta = [a - b for a, b in zip(after, base)]
        if codec == "none":
            wire_payload = encode_leaves(delta)
            c_uplink.inc(float(sum(np.asarray(d).nbytes for d in delta)))
        else:
            # the error-feedback residual lives HERE, at the encoding
            # edge: fold last round's dropped mass into this round's
            # delta before encoding, bank what this encode drops
            acc = (
                [d + r for d, r in zip(delta, ef_residual)]
                if use_ef and ef_residual is not None
                else delta
            )
            payloads = [
                encode_leaf(
                    a, codec, cfg.fed.dcn_topk_ratio,
                    sketch_width=cfg.fed.dcn_sketch_width,
                    sketch_seed=cfg.fed.dcn_sketch_seed, leaf_id=j,
                )
                for j, a in enumerate(acc)
            ]
            if use_ef:
                ef_residual = [
                    a - decode_leaf(p, codec, a.shape, leaf_id=j)
                    for j, (a, p) in enumerate(zip(acc, payloads))
                ]
            wire_payload = encode_payloads(payloads)
            c_uplink.inc(float(sum(payload_nbytes(p) for p in payloads)))
        # the push request captures based_on NOW — the version this
        # round's delta was actually computed against — because the
        # backlog flush below can resync and advance `version` under us
        push_req = {
            "cmd": "push", "worker": worker_id, "round": round_idx,
            "epoch": epoch, "based_on": version, "weight": 1.0,
            "payload": wire_payload, "codec": codec,
            # generated once per contribution; a retry reuses it verbatim
            "push_id": new_push_id(worker_id, round_idx),
        }
        if straggle_s > 0:
            print(
                f"[agg-worker {worker_id}] straggling "
                f"{straggle_s:.1f}s before the round-{round_idx} push",
                flush=True,
            )
            time.sleep(straggle_s)

        # any backlog first (arrival order), so a recovered wire folds
        # contributions oldest-first and this round's push lands last;
        # a bump seen here means the authority restarted while we were
        # degraded — run the recovery handshake before the fresh push
        if unacked and flush_unacked():
            adopted_this_round = resync("incarnation bump") \
                or adopted_this_round
        with trainer.tracer.span("agg.push", round=round_idx,
                                 based_on=version):
            try:
                resp = rpc.call(push_req, op="push")
            except OSError as e:
                # the wire is down: park the contribution (same push_id
                # on the eventual retry) and keep training degraded
                unacked.append(push_req)
                print(
                    f"[agg-worker {worker_id}] authority unreachable for "
                    f"round-{round_idx} push ({e.__class__.__name__}); "
                    f"parked ({len(unacked)} unacked), training on",
                    flush=True,
                )
                check_budget(e)
                resp = None
            except ValueError as e:
                if "rebase" in str(e) or "ahead of" in str(e):
                    # the authority restarted BEHIND us: our base version
                    # no longer exists, so this delta is unfoldable —
                    # drop it and adopt the restored global
                    print(
                        f"[agg-worker {worker_id}] round-{round_idx} push "
                        f"refused ({e}); dropping it and resyncing",
                        flush=True,
                    )
                    adopted_this_round = resync("rebase reply")
                    resp = None
                else:
                    raise
        if resp is not None:
            c_pushes.inc()
            g_staleness.set(float(max(0, int(resp["version"]) - version)))
            if note_incarnation(resp):
                # the restarted authority ACCEPTED this push; re-hello
                # and adopt its restored global before the next round
                adopted_this_round = resync("incarnation bump") \
                    or adopted_this_round

        # bounded wait for a commit NEWER than our base; timing out is
        # the async contract (train on, push staler next round)
        deadline = time.monotonic() + global_wait_s
        new_version, payload, commit_flow = version, None, None
        while time.monotonic() < deadline:
            try:
                resp = rpc.call(
                    {"cmd": "global", "since": version}, op="global"
                )
            except OSError as e:
                # a dead wire makes the poll pointless — proceed stale
                # now, the next round's flush/push probes recovery
                check_budget(e)
                break
            if note_incarnation(resp):
                adopted_this_round = resync("incarnation bump") \
                    or adopted_this_round
                break
            if "payload" in resp:
                new_version, payload = int(resp["version"]), resp["payload"]
                # the commit's flow id rides the reply ENVELOPE: finish
                # the server's commit arrow inside our adoption span
                reply_env = wire.last_reply_envelope()
                if reply_env is not None:
                    commit_flow = reply_env.get("commit_flow")
                break
            time.sleep(poll_s)
        if payload is not None:
            with trainer.tracer.span("agg.adopt", version=new_version,
                                     round=round_idx):
                if commit_flow is not None:
                    trainer.tracer.flow("in", int(commit_flow))
                base = decode_leaves(payload)
                version = new_version
                _adopt(trainer, treedef, base)
            g_version.set(float(version))
        elif not adopted_this_round:
            base = after
            print(
                f"[agg-worker {worker_id}] no commit within "
                f"{global_wait_s:.0f}s after round {round_idx}; "
                "proceeding stale",
                flush=True,
            )

    # one last delivery attempt for anything still parked — after this
    # the contribution is gone with the process, so say so
    if unacked:
        flush_unacked()
        if unacked:
            print(
                f"[agg-worker {worker_id}] exiting with {len(unacked)} "
                "undelivered pushes (authority still unreachable)",
                flush=True,
            )

    # the run()-loop's exit-path bookkeeping: artifacts + final push.
    # One bounded retry each — the exit path is the last chance to bank
    # the round history, so a transient FS/wire hiccup gets a second try
    if trainer._obs_dir is not None:
        from fedrec_tpu.obs import dump_artifacts

        for attempt in (0, 1):
            try:
                dump_artifacts(
                    trainer._obs_dir, registry=trainer.registry,
                    tracer=trainer.tracer,
                )
                break
            except OSError as e:
                if attempt == 0:
                    time.sleep(0.5)
                    continue
                print(f"[agg-worker {worker_id}] could not write obs "
                      f"artifacts: {e}", flush=True)
    if trainer.fleet_pusher is not None:
        trainer.fleet_pusher.push(final=True)
    try:
        trainer.logger.finish()
    except Exception as e:  # noqa: BLE001 — a flush error must not fail the run
        print(f"[agg-worker {worker_id}] logger.finish failed: {e}",
              flush=True)
    return history


def _adopt(trainer, treedef, leaves: list[np.ndarray]) -> None:
    user_params, news_params = jax.tree_util.tree_unflatten(treedef, leaves)
    trainer.set_global_params(user_params, news_params)
