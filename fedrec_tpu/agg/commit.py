"""Quorum commit + staleness-weighted folding — `agg.mode` = "async".

A commit advances the global from version ``v`` to ``v+1`` by folding a
set of buffered :class:`~fedrec_tpu.agg.buffer.BufferEntry` deltas:

    staleness(e) = v - e.based_on          (commits behind the global)
    w~(e)        = e.weight / (1 + staleness(e))
    global'      = global + reduce_e(w~, delta_e)

where ``reduce`` is the participation-weighted mean for
``fed.robust.method == "mean"`` (so a zero-staleness all-reporting
commit is EXACTLY the FedAvg update the flat synchronous path computes
— FedAvg/FedOpt server state sees identical update semantics, and
``ServerOptimizer.step(round_start, proposal)`` composes unchanged), or
:func:`~fedrec_tpu.fed.robust.robust_reduce_tree_np` over the delta
stacks for robust methods.  The 1/(1+staleness) polynomial decay is the
FedBuff/FedAsync standard: a late delta was computed against an older
base, so folding it against the NEW base is an approximation whose
error grows with staleness — the decay bounds it, and entries past
`agg.staleness_cap` are dropped outright (``stale_drops``).

Codec composition (``fed.dcn_compress`` x ``agg.mode='async'``): an
entry tagged with a LINEAR sketch codec carries per-leaf sketch arrays
and folds IN SKETCH SPACE — the staleness-weighted sum runs over the
sketches and each leaf decodes exactly ONCE per commit, which by
linearity equals decoding every contribution first (the
decode-after-sum identity, pinned in ``tests/test_agg.py``).
Per-contribution codecs (int8/sign1bit/topk) never reach the fold
encoded: :func:`encode_contribution` decodes them AT PUSH TIME with
per-edge error-feedback residuals, so their entries arrive dense
(``codec="none"``) and staleness reordering moves only weights, never
the reconstruction.  Robust non-mean methods need per-contribution
deltas to rank, so a sketch entry under a robust fold is a hard
ValueError, mirroring the synchronous coordinator's guard.
"""

from __future__ import annotations

import time
from dataclasses import dataclass

import jax
import numpy as np

from fedrec_tpu.agg.buffer import BufferEntry
from fedrec_tpu.comms import (
    SKETCH_PAYLOAD_KEY,
    codec_caps,
    decode_leaf,
    encode_leaf,
    payload_nbytes,
    validate_codec,
)
from fedrec_tpu.fed.robust import robust_reduce_tree_np

__all__ = [
    "CommitPolicy",
    "CommitStats",
    "encode_contribution",
    "fold_commit",
    "staleness_weight",
]


@dataclass
class CommitPolicy:
    """`agg.quorum` / `agg.staleness_cap` as one value object."""

    quorum: int = 0                # 0 = all-reporting
    staleness_cap: int = 2

    def quorum_for(self, world: int) -> int:
        """The effective commit quorum under the CURRENT membership
        world: a shrink below the configured quorum must not deadlock
        the commit loop (quorum clamps to the surviving world)."""
        if world < 1:
            raise ValueError(f"quorum needs a world >= 1, got {world}")
        k = self.quorum if self.quorum > 0 else world
        return max(1, min(k, world))


@dataclass
class CommitStats:
    version: int = 0               # the version this commit produced
    folded: int = 0                # entries folded into the commit
    late_folds: int = 0            # folded entries with staleness > 0
    stale_drops: int = 0           # entries dropped past the cap
    mean_staleness: float = 0.0
    max_staleness: int = 0
    fold_ms: float = 0.0


def staleness_weight(staleness: int) -> float:
    """FedBuff's polynomial decay: 1/(1+s), s in commits behind."""
    return 1.0 / (1.0 + max(0, int(staleness)))


def encode_contribution(
    delta_leaves: list[np.ndarray],
    codec: str,
    *,
    topk_ratio: float = 0.01,
    sketch_width: float = 0.1,
    sketch_seed: int = 0,
    residual_leaves: list[np.ndarray] | None = None,
) -> tuple[list[np.ndarray], str, list[np.ndarray] | None, int]:
    """Run one edge's dense delta through ``fed.dcn_compress`` for the
    async buffer.  Returns ``(entry_leaves, entry_codec,
    new_residual_leaves, encoded_nbytes)``:

    - ``codec="none"``: the delta passes through dense;
      ``encoded_nbytes`` is the real f32 wire cost.
    - per-contribution codecs (int8/sign1bit/topk): encode then decode
      IMMEDIATELY (decode-at-push) — the entry buffers dense
      (``entry_codec="none"``) so staleness-reordered folds are pure
      weight arithmetic.  Codecs with error-feedback support add the
      banked ``residual_leaves`` BEFORE encoding and return the new
      residual (what the encode dropped) for the caller to bank
      against the version this contribution was based on.
    - linear sketches (countsketch/randproj): the entry leaves ARE the
      per-leaf sketch arrays (``entry_codec=codec``); the fold sums
      them in sketch space and :func:`fold_commit` decodes once per
      commit.  No residual — the sketch is unbiased, there is no
      systematic dropped mass to feed back.

    ``encoded_nbytes`` is measured from the payloads actually built
    (``payload_nbytes``), not dtype arithmetic — it is the uplink
    number the agg-scale benchmark banks.
    """
    delta_leaves = [np.asarray(x, np.float32) for x in delta_leaves]
    if codec == "none":
        return (
            delta_leaves,
            "none",
            None,
            int(sum(x.nbytes for x in delta_leaves)),
        )
    validate_codec(codec)
    caps = codec_caps(codec)
    if not caps.decodes_per_contribution:
        key = SKETCH_PAYLOAD_KEY[codec]
        payloads = [
            encode_leaf(
                x, codec, sketch_width=sketch_width,
                sketch_seed=sketch_seed, leaf_id=j,
            )
            for j, x in enumerate(delta_leaves)
        ]
        nbytes = int(sum(payload_nbytes(p) for p in payloads))
        return [p[key] for p in payloads], codec, None, nbytes

    use_ef = caps.supports_error_feedback and residual_leaves is not None
    acc = (
        [d + np.asarray(r, np.float32)
         for d, r in zip(delta_leaves, residual_leaves)]
        if use_ef
        else delta_leaves
    )
    decoded, new_residual, nbytes = [], [], 0
    for j, a in enumerate(acc):
        payload = encode_leaf(a, codec, topk_ratio, leaf_id=j)
        nbytes += payload_nbytes(payload)
        d = decode_leaf(payload, codec, a.shape, leaf_id=j)
        decoded.append(d)
        new_residual.append(a - d)
    residual_out = new_residual if caps.supports_error_feedback else None
    return decoded, "none", residual_out, int(nbytes)


def fold_commit(
    base_leaves: list[np.ndarray],
    entries: list[BufferEntry],
    version: int,
    policy: CommitPolicy,
    method: str = "mean",
    trim_k: int = 1,
    clip_norm: float = 10.0,
    sketch_seed: int = 0,
) -> tuple[list[np.ndarray], CommitStats]:
    """Fold ``entries`` into ``base_leaves`` (the version-``version``
    global, as an ordered leaf list) and return the version-``version+1``
    leaves plus the commit accounting.  Entries past the staleness cap
    are dropped, never folded; an all-dropped commit returns the base
    unchanged at the bumped version (the global advances so the
    droppers' staleness keeps growing — matching a quorum of on-time
    entries arriving with nothing foldable).

    Entries tagged with a linear sketch codec fold in sketch space:
    their staleness-weighted sum runs over the per-leaf sketch arrays
    and each leaf decodes ONCE (``sketch_seed`` must match the
    encoders' — the shared hash geometry).  Dense entries and sketch
    entries share one weight normalizer, so a mixed buffer is still a
    single weighted mean."""
    t0 = time.monotonic()
    stats = CommitStats(version=version + 1)
    fold: list[BufferEntry] = []
    stales: list[int] = []
    for e in entries:
        s = version - e.based_on
        if s < 0:
            raise ValueError(
                f"entry from {e.worker!r} based_on={e.based_on} is ahead of "
                f"the global version {version}"
            )
        if s > policy.staleness_cap:
            stats.stale_drops += 1
            continue
        fold.append(e)
        stales.append(s)
    if not fold:
        stats.fold_ms = (time.monotonic() - t0) * 1e3
        return [np.asarray(x) for x in base_leaves], stats

    sketch_codecs = sorted({e.codec for e in fold if e.codec != "none"})
    if method != "mean" and sketch_codecs:
        raise ValueError(
            f"fed.robust.method={method!r} cannot fold sketch-coded "
            f"entries (codecs {sketch_codecs} in the buffer): order "
            "statistics rank per-contribution deltas, but a sketch "
            "entry's contribution only exists after the summed decode. "
            "Push per-contribution codecs (int8/sign1bit/topk) or "
            "fed.dcn_compress='none' to async workers under a robust "
            "fold, or set fed.robust.method='mean'."
        )

    w = np.asarray(
        [e.weight * staleness_weight(s) for e, s in zip(fold, stales)],
        np.float64,
    )
    stats.folded = len(fold)
    stats.late_folds = sum(1 for s in stales if s > 0)
    stats.mean_staleness = float(np.mean(stales))
    stats.max_staleness = int(max(stales))

    wmask = w > 0
    total = float(np.sum(w * wmask))
    if method == "mean" or total == 0.0:
        if total == 0.0:
            delta = [
                np.zeros_like(np.asarray(b, np.float64)) for b in base_leaves
            ]
        else:
            num = [
                np.zeros(np.asarray(b).shape, np.float64)
                for b in base_leaves
            ]
            dense_ix = [i for i, e in enumerate(fold) if e.codec == "none"]
            if dense_ix:
                wd = (w * wmask)[dense_ix]
                md = wmask[dense_ix]
                for j in range(len(base_leaves)):
                    stack = np.stack(
                        [
                            np.asarray(fold[i].leaves[j], np.float64)
                            for i in dense_ix
                        ],
                        axis=0,
                    )
                    num[j] += np.einsum(
                        "p,p...->...",
                        wd,
                        np.where(
                            md.reshape((-1,) + (1,) * (stack.ndim - 1)),
                            stack,
                            0.0,
                        ),
                    )
            for codec in sketch_codecs:
                ix = [i for i, e in enumerate(fold) if e.codec == codec]
                ws = (w * wmask)[ix]
                key = SKETCH_PAYLOAD_KEY[codec]
                for j, b in enumerate(base_leaves):
                    # the staleness-weighted reduce runs over SKETCHES;
                    # one decode per (codec, leaf) per commit
                    sk = np.einsum(
                        "p,p...->...",
                        ws,
                        np.stack(
                            [
                                np.asarray(fold[i].leaves[j], np.float64)
                                for i in ix
                            ],
                            axis=0,
                        ),
                    )
                    num[j] += np.asarray(
                        decode_leaf(
                            {key: sk.astype(np.float32)},
                            codec,
                            tuple(np.asarray(b).shape),
                            sketch_seed=sketch_seed,
                            leaf_id=j,
                        ),
                        np.float64,
                    )
            delta = [n / total for n in num]
    else:
        # robust methods reduce the delta stacks directly; fallback 0
        # (an all-non-finite coordinate leaves the global untouched) —
        # the sketch guard above guarantees every entry here is dense
        stacks = [
            np.stack(
                [np.asarray(e.leaves[j], np.float64) for e in fold], axis=0
            )
            for j in range(len(base_leaves))
        ]
        reduced = robust_reduce_tree_np(
            stacks,
            w,
            method,
            trim_k=trim_k,
            clip_norm=clip_norm,
            fallback_tree=[np.zeros_like(np.asarray(b)) for b in base_leaves],
        )
        delta = list(jax.tree_util.tree_flatten(reduced)[0])
    out = [
        np.asarray(b, np.float64) + d for b, d in zip(base_leaves, delta)
    ]
    out = [o.astype(np.asarray(b).dtype) for o, b in zip(out, base_leaves)]
    stats.fold_ms = (time.monotonic() - t0) * 1e3
    return out, stats
