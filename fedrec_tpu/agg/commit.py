"""Quorum commit + staleness-weighted folding — `agg.mode` = "async".

A commit advances the global from version ``v`` to ``v+1`` by folding a
set of buffered :class:`~fedrec_tpu.agg.buffer.BufferEntry` deltas:

    staleness(e) = v - e.based_on          (commits behind the global)
    w~(e)        = e.weight / (1 + staleness(e))
    global'      = global + reduce_e(w~, delta_e)

where ``reduce`` is the participation-weighted mean for
``fed.robust.method == "mean"`` (so a zero-staleness all-reporting
commit is EXACTLY the FedAvg update the flat synchronous path computes
— FedAvg/FedOpt server state sees identical update semantics, and
``ServerOptimizer.step(round_start, proposal)`` composes unchanged), or
:func:`~fedrec_tpu.fed.robust.robust_reduce_tree_np` over the delta
stacks for robust methods.  The 1/(1+staleness) polynomial decay is the
FedBuff/FedAsync standard: a late delta was computed against an older
base, so folding it against the NEW base is an approximation whose
error grows with staleness — the decay bounds it, and entries past
`agg.staleness_cap` are dropped outright (``stale_drops``).
"""

from __future__ import annotations

import time
from dataclasses import dataclass

import jax
import numpy as np

from fedrec_tpu.agg.buffer import BufferEntry
from fedrec_tpu.fed.robust import robust_reduce_tree_np

__all__ = ["CommitPolicy", "CommitStats", "fold_commit", "staleness_weight"]


@dataclass
class CommitPolicy:
    """`agg.quorum` / `agg.staleness_cap` as one value object."""

    quorum: int = 0                # 0 = all-reporting
    staleness_cap: int = 2

    def quorum_for(self, world: int) -> int:
        """The effective commit quorum under the CURRENT membership
        world: a shrink below the configured quorum must not deadlock
        the commit loop (quorum clamps to the surviving world)."""
        if world < 1:
            raise ValueError(f"quorum needs a world >= 1, got {world}")
        k = self.quorum if self.quorum > 0 else world
        return max(1, min(k, world))


@dataclass
class CommitStats:
    version: int = 0               # the version this commit produced
    folded: int = 0                # entries folded into the commit
    late_folds: int = 0            # folded entries with staleness > 0
    stale_drops: int = 0           # entries dropped past the cap
    mean_staleness: float = 0.0
    max_staleness: int = 0
    fold_ms: float = 0.0


def staleness_weight(staleness: int) -> float:
    """FedBuff's polynomial decay: 1/(1+s), s in commits behind."""
    return 1.0 / (1.0 + max(0, int(staleness)))


def fold_commit(
    base_leaves: list[np.ndarray],
    entries: list[BufferEntry],
    version: int,
    policy: CommitPolicy,
    method: str = "mean",
    trim_k: int = 1,
    clip_norm: float = 10.0,
) -> tuple[list[np.ndarray], CommitStats]:
    """Fold ``entries`` into ``base_leaves`` (the version-``version``
    global, as an ordered leaf list) and return the version-``version+1``
    leaves plus the commit accounting.  Entries past the staleness cap
    are dropped, never folded; an all-dropped commit returns the base
    unchanged at the bumped version (the global advances so the
    droppers' staleness keeps growing — matching a quorum of on-time
    entries arriving with nothing foldable)."""
    t0 = time.monotonic()
    stats = CommitStats(version=version + 1)
    fold: list[BufferEntry] = []
    stales: list[int] = []
    for e in entries:
        s = version - e.based_on
        if s < 0:
            raise ValueError(
                f"entry from {e.worker!r} based_on={e.based_on} is ahead of "
                f"the global version {version}"
            )
        if s > policy.staleness_cap:
            stats.stale_drops += 1
            continue
        fold.append(e)
        stales.append(s)
    if not fold:
        stats.fold_ms = (time.monotonic() - t0) * 1e3
        return [np.asarray(x) for x in base_leaves], stats

    w = np.asarray(
        [e.weight * staleness_weight(s) for e, s in zip(fold, stales)],
        np.float64,
    )
    stats.folded = len(fold)
    stats.late_folds = sum(1 for s in stales if s > 0)
    stats.mean_staleness = float(np.mean(stales))
    stats.max_staleness = int(max(stales))

    stacks = [
        np.stack([np.asarray(e.leaves[j], np.float64) for e in fold], axis=0)
        for j in range(len(base_leaves))
    ]
    total = float(np.sum(w * (w > 0)))
    if method == "mean" or total == 0.0:
        if total == 0.0:
            delta = [np.zeros_like(np.asarray(b, np.float64)) for b in base_leaves]
        else:
            wmask = w > 0
            delta = [
                np.einsum(
                    "p,p...->...",
                    w * wmask,
                    np.where(
                        wmask.reshape((-1,) + (1,) * (s.ndim - 1)), s, 0.0
                    ),
                )
                / total
                for s in stacks
            ]
    else:
        # robust methods reduce the delta stacks directly; fallback 0
        # (an all-non-finite coordinate leaves the global untouched)
        reduced = robust_reduce_tree_np(
            stacks,
            w,
            method,
            trim_k=trim_k,
            clip_norm=clip_norm,
            fallback_tree=[np.zeros_like(np.asarray(b)) for b in base_leaves],
        )
        delta = list(jax.tree_util.tree_flatten(reduced)[0])
    out = [
        np.asarray(b, np.float64) + d for b, d in zip(base_leaves, delta)
    ]
    out = [o.astype(np.asarray(b).dtype) for o, b in zip(out, base_leaves)]
    stats.fold_ms = (time.monotonic() - t0) * 1e3
    return out, stats
