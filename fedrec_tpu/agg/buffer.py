"""Server-side contribution buffer — the state behind `agg.mode` = "async".

Each entry is one worker's round contribution: a DELTA against the
global version it trained from (``based_on``), tagged with the
membership epoch it was produced under, its aggregation weight, and its
arrival time.  Entries wait here until :mod:`fedrec_tpu.agg.commit`
folds them — on time at quorum, or staleness-weighted into a later
commit, or dropped past `agg.staleness_cap`.

Contribution payloads are ORDERED LEAF LISTS (plain ``np.ndarray``
lists), not structured pytrees: the buffer and the commit fold never
need the tree structure, only per-leaf arithmetic, so callers flatten
with their own treedef and unflatten the committed result.  That keeps
the wire format (npz of positional leaves) and the checkpoint sidecar
model-agnostic.

Entries carry a ``codec`` tag naming the REPRESENTATION their leaves
are in — ``"none"`` for dense deltas (per-contribution codecs like
int8/sign1bit/topk are decoded at push time, so their entries land
here dense), or a linear sketch codec (``countsketch``/``randproj``)
whose per-leaf sketch arrays fold IN SKETCH SPACE at commit and decode
exactly once (see :func:`fedrec_tpu.agg.commit.fold_commit`).

The buffer also banks per-edge error-feedback residuals
(``ef_residuals``, worker id -> the dense residual the edge's last
encode left behind, tagged with the global version it was based on).
They ride the same npz sidecar as the pending entries, so a restart or
a membership-epoch reform preserves exactly the residuals whose
workers survived — a dead worker's residual is dropped with its
pending entry.

The buffer checkpoints beside the model snapshot
(``agg_buffer.npz`` via :meth:`AggBuffer.state_bytes` /
:meth:`AggBuffer.load_state`, the same round-tagged sidecar discipline
as the FedOpt server state): pending late contributions survive a
restart, and a worker death mid-buffer only costs that worker's pending
entry — the shrink-then-commit path is pinned in ``tests/test_agg.py``.
"""

from __future__ import annotations

import io
import json
from dataclasses import dataclass, field

import numpy as np

__all__ = ["AGG_BUFFER_SIDECAR", "AggBuffer", "BufferEntry"]

# the checkpoint sidecar's name beside the model snapshot (the same
# round-tagged discipline as server_opt_state.msgpack)
AGG_BUFFER_SIDECAR = "agg_buffer.npz"

_MAGIC = "fedrec-agg-buffer-v1"


@dataclass
class BufferEntry:
    """One worker's pending round contribution (a delta vs ``based_on``)."""

    worker: str
    round: int
    epoch: int                      # membership epoch the delta was produced under
    based_on: int                   # global version the worker trained from
    weight: float
    arrival_ms: float               # simulated/measured arrival latency
    leaves: list = field(default_factory=list)  # ordered np.ndarray leaf list
    # the representation `leaves` is in: "none" = dense delta leaves;
    # a linear sketch codec name = per-leaf sketch arrays that fold in
    # sketch space (per-contribution codecs decode at push, so they
    # never appear here — their entries are already dense)
    codec: str = "none"
    # client-generated idempotency token (parallel.rpc.new_push_id):
    # retries of the same contribution reuse it, the commit authority's
    # ledger folds a given id at most once. "" = pre-resilient-RPC push.
    push_id: str = ""


class AggBuffer:
    """Epoch-keyed pending-contribution store with sidecar persistence."""

    def __init__(self, epoch: int = 0):
        self.epoch = int(epoch)
        self.entries: list[BufferEntry] = []
        # worker id -> {"based_on": int, "leaves": [np.ndarray, ...]}:
        # the dense encode residual the edge banked at its last push
        # (error feedback for per-contribution codecs), tagged with the
        # global version the encoded contribution was based on so a
        # restore knows which commit the correction belongs to
        self.ef_residuals: dict[str, dict] = {}

    def __len__(self) -> int:
        return len(self.entries)

    def add(self, entry: BufferEntry) -> BufferEntry | None:
        """A worker re-pushing for the same round replaces its stale
        pending entry (retries after a torn connection must not double
        its weight).  Returns the REPLACED entry when one existed (the
        authority's push ledger accounts its ``push_id`` as superseded
        — or as a duplicate delivery when the ids match), else None."""
        replaced: BufferEntry | None = None
        kept: list[BufferEntry] = []
        for e in self.entries:
            if e.worker == entry.worker and e.round == entry.round:
                replaced = e
            else:
                kept.append(e)
        kept.append(entry)
        self.entries = kept
        return replaced

    def pending_workers(self) -> set[str]:
        return {e.worker for e in self.entries}

    def bank_residual(
        self, worker: str, based_on: int, leaves: list
    ) -> None:
        """Bank the edge's encode residual against the version its
        contribution was based on — a re-push replaces it (same
        replace-don't-double rule as :meth:`add`)."""
        self.ef_residuals[str(worker)] = {
            "based_on": int(based_on),
            "leaves": [np.asarray(x) for x in leaves],
        }

    def residual_for(self, worker: str) -> list | None:
        """The dense residual banked for ``worker``, or ``None``."""
        banked = self.ef_residuals.get(str(worker))
        return None if banked is None else banked["leaves"]

    def take_all(self) -> list[BufferEntry]:
        out, self.entries = self.entries, []
        return out

    def advance_epoch(self, epoch: int, drop_dead: set[str] | None = None) -> int:
        """Membership reformed: adopt the new epoch and drop pending
        entries from workers that did not survive it (their deltas were
        produced by a peer that no longer exists — folding them would
        resurrect a dead member's weight).  Entries from survivors stay
        buffered and fold with staleness weighting; so do their banked
        error-feedback residuals (a dead worker's residual goes with
        its entry — there is no future push to correct).  Returns the
        number of ENTRIES dropped."""
        if epoch < self.epoch:
            raise ValueError(
                f"membership epoch moved backwards: {self.epoch} -> {epoch}"
            )
        self.epoch = int(epoch)
        if not drop_dead:
            return 0
        before = len(self.entries)
        self.entries = [e for e in self.entries if e.worker not in drop_dead]
        for w in drop_dead:
            self.ef_residuals.pop(str(w), None)
        return before - len(self.entries)

    # ------------------------------------------------------- persistence
    def state_bytes(self, round_idx: int, version: int) -> bytes:
        """Round-tagged npz sidecar (one blob, atomically writable).
        The ``codec`` tag and the ``residuals`` section are additive —
        a pre-codec (v1) blob simply has neither and loads as all-dense
        with no banked residuals."""
        residual_workers = sorted(self.ef_residuals)
        meta = {
            "magic": _MAGIC,
            "round": int(round_idx),
            "version": int(version),
            "epoch": self.epoch,
            "entries": [
                {
                    "worker": e.worker,
                    "round": e.round,
                    "epoch": e.epoch,
                    "based_on": e.based_on,
                    "weight": float(e.weight),
                    "arrival_ms": float(e.arrival_ms),
                    "num_leaves": len(e.leaves),
                    "codec": e.codec,
                    "push_id": e.push_id,
                }
                for e in self.entries
            ],
            "residuals": [
                {
                    "worker": w,
                    "based_on": int(self.ef_residuals[w]["based_on"]),
                    "num_leaves": len(self.ef_residuals[w]["leaves"]),
                }
                for w in residual_workers
            ],
        }
        arrays = {
            f"e{i}_leaf{j}": np.asarray(leaf)
            for i, e in enumerate(self.entries)
            for j, leaf in enumerate(e.leaves)
        }
        arrays.update(
            {
                f"r{k}_leaf{j}": np.asarray(leaf)
                for k, w in enumerate(residual_workers)
                for j, leaf in enumerate(self.ef_residuals[w]["leaves"])
            }
        )
        buf = io.BytesIO()
        np.savez(
            buf, __meta__=np.frombuffer(json.dumps(meta).encode(), np.uint8),
            **arrays,
        )
        return buf.getvalue()

    @classmethod
    def load_state(cls, blob: bytes) -> tuple["AggBuffer", int, int]:
        """Returns ``(buffer, round, version)`` from :meth:`state_bytes`
        output; raises ``ValueError`` on a foreign or torn blob (the
        caller decides whether a round-tag mismatch warrants starting
        empty — late contributions are droppable by design)."""
        with np.load(io.BytesIO(blob)) as z:
            try:
                meta = json.loads(bytes(z["__meta__"].tobytes()).decode())
            except (KeyError, json.JSONDecodeError) as e:
                raise ValueError(f"not an agg-buffer sidecar: {e}") from e
            if meta.get("magic") != _MAGIC:
                raise ValueError(
                    f"not an agg-buffer sidecar (magic={meta.get('magic')!r})"
                )
            buf = cls(epoch=meta["epoch"])
            for i, ent in enumerate(meta["entries"]):
                leaves = [
                    np.asarray(z[f"e{i}_leaf{j}"])
                    for j in range(ent["num_leaves"])
                ]
                buf.entries.append(
                    BufferEntry(
                        worker=ent["worker"],
                        round=int(ent["round"]),
                        epoch=int(ent["epoch"]),
                        based_on=int(ent["based_on"]),
                        weight=float(ent["weight"]),
                        arrival_ms=float(ent["arrival_ms"]),
                        leaves=leaves,
                        codec=str(ent.get("codec", "none")),
                        push_id=str(ent.get("push_id", "")),
                    )
                )
            for k, res in enumerate(meta.get("residuals", [])):
                buf.ef_residuals[str(res["worker"])] = {
                    "based_on": int(res["based_on"]),
                    "leaves": [
                        np.asarray(z[f"r{k}_leaf{j}"])
                        for j in range(res["num_leaves"])
                    ],
                }
        return buf, int(meta["round"]), int(meta["version"])
