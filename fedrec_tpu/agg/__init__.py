"""Round-end aggregation topologies (`agg.mode`).

The flat all-reporting reduce every prior PR shipped is one point in a
design space with two more:

  * **hierarchical** (:mod:`.hierarchy`) — tiered robust reduce whose
    critical path is O(log_fanout P) instead of O(P).  With the plain
    weighted mean the tier tree of (sum(w*x), sum(w)) partials is
    *algebraically* the flat mean, so that case lowers to the unchanged
    flat collective and stays bit-identical; per-tier trimming/medians
    genuinely diverge (docs/DESIGN.md, "Removing the round barrier").
  * **async** (:mod:`.buffer` + :mod:`.commit`) — buffered quorum
    commit: the global advances once ``agg.quorum`` contributions land,
    stragglers fold staleness-weighted into the NEXT commit (dropped
    past ``agg.staleness_cap``), and the straggler's marginal ``gate_ms``
    goes to ~0.  :mod:`.server` / :mod:`.worker` are the multi-process
    deployment (TCP JSON-lines, same wire idiom as the membership
    service); the Trainer also runs the same commit policy in-process
    for single-host cohort simulation.
"""

from fedrec_tpu.agg.buffer import AggBuffer, BufferEntry
from fedrec_tpu.agg.commit import CommitPolicy, CommitStats, fold_commit, staleness_weight
from fedrec_tpu.agg.hierarchy import build_tree, tree_critical_path_ms, tree_reduce_np

__all__ = [
    "AggBuffer",
    "BufferEntry",
    "CommitPolicy",
    "CommitStats",
    "build_tree",
    "fold_commit",
    "staleness_weight",
    "tree_critical_path_ms",
    "tree_reduce_np",
]
