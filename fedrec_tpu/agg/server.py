"""Standalone buffered-aggregation service — the async deployment's
commit authority (`agg.mode` = "async" across processes).

The synchronous deployments aggregate inside an all-process collective,
which is exactly the barrier async mode removes — so async workers do
NOT form a collective world at all.  Each runs a single-process Trainer
and speaks to this service over the fleet's TCP JSON-lines wire idiom
(:func:`~fedrec_tpu.obs.fleet.serve_json_line`, the same exchange the
membership service and telemetry collector use):

    hello  {worker, epoch}                 -> {version, quorum, have_global,
                                               incarnation}
    init   {worker, payload}               -> {version, incarnation}
    push   {worker, round, epoch, based_on,
            weight, payload[, codec]
            [, push_id]}                   -> {version, committed,
                                               incarnation[, duplicate]}
    global {since}                         -> {version[, payload], incarnation}
    status {}                              -> commit/gate/buffer/ledger accounting

Payloads are base64 npz blobs of ORDERED leaf lists (the buffer's
model-agnostic contract).  A push lands in the :class:`AggBuffer`; once
``agg.quorum`` distinct workers are pending the commit fires through
:func:`~fedrec_tpu.agg.commit.fold_commit` — stragglers' later pushes
fold staleness-weighted into the NEXT commit.

A push may declare a ``codec`` (``fed.dcn_compress`` on the worker):
its payload is then a base64 npz of per-leaf ENCODED payload dicts
(``p{i}__{key}`` arrays) instead of dense leaves.  Per-contribution
codecs (int8/sign1bit/topk) are decoded AT PUSH TIME against the
global's leaf shapes — the worker holds its own error-feedback
residual, the server only densifies — while linear sketches
(countsketch/randproj) buffer as raw sketch arrays and fold in sketch
space at commit, decoding once (``--sketch-seed`` must match the
workers' ``fed.dcn_sketch_seed``).  ``agg.push_bytes_total`` counts
the wire bytes actually received per worker — the uplink number the
async-compression claim rests on.

Gate accounting (the before/after panel's "after" side): per commit the
quorum-CLOSING arrival is charged ``t_K - t_{K-1}`` — the marginal
delay it inflicted on the commit, the async analogue of the barrier
deployment's ``gate_ms`` attribution — and every other worker is
charged 0.  A chaos-delayed worker never closes a quorum, so its gate
pins to ~0 (``scripts/async_smoke.sh`` asserts exactly this).

Buffer state persists to ``--state-dir`` after every state change (the
checkpoint sidecar discipline), so pending late contributions survive a
service restart.  Crash recovery goes further: ``agg_global.npz`` beside
the buffer sidecar carries ``{global leaves, version, incarnation,
push ledger}`` at commit cadence, so a restarted authority RESUMES at
the committed version instead of forgetting the global (the old
"push before init" dead end).  Every reply advertises the authority's
**incarnation** (a restart-bumped counter, also echoed in the reply
envelope) — a worker seeing the bump re-hellos and resumes pushing.

Pushes carry a client-generated idempotent ``push_id``
(:func:`fedrec_tpu.parallel.rpc.new_push_id`); the authority's **push
ledger** records each acked push's terminal disposition (``folded`` /
``stale_dropped`` / ``superseded``) exactly once, and a re-delivered id
that already reached a disposition is dropped as a duplicate
(``agg.push_dups_total``) — retried and chaos-duplicated pushes can
never double-fold.  ``benchmarks/churn_soak.py`` reconciles worker-side
acks against this ledger for its zero-acked-push-loss claim.
"""

from __future__ import annotations

import base64
import io
import json
import socket
import threading
import time

import numpy as np

from fedrec_tpu.agg.buffer import AggBuffer, BufferEntry
from fedrec_tpu.agg.commit import CommitPolicy, fold_commit
from fedrec_tpu.obs import wire as wireobs
from fedrec_tpu.obs.tracing import get_tracer
from fedrec_tpu.comms import (
    SKETCH_PAYLOAD_KEY,
    codec_caps,
    decode_leaf,
    validate_codec,
)

__all__ = [
    "AggServer",
    "decode_leaves",
    "decode_payloads",
    "encode_leaves",
    "encode_payloads",
    "main",
]


def encode_leaves(leaves: list[np.ndarray]) -> str:
    buf = io.BytesIO()
    np.savez(buf, **{f"leaf{i}": np.asarray(x) for i, x in enumerate(leaves)})
    return base64.b64encode(buf.getvalue()).decode()


def decode_leaves(payload: str) -> list[np.ndarray]:
    with np.load(io.BytesIO(base64.b64decode(payload))) as z:
        return [np.asarray(z[f"leaf{i}"]) for i in range(len(z.files))]


def encode_payloads(payloads: list[dict]) -> str:
    """Encoded-contribution wire blob: each leaf's codec payload dict is
    flattened to ``p{i}__{key}`` arrays in one npz — the compressed twin
    of :func:`encode_leaves` (same transport, different contents)."""
    buf = io.BytesIO()
    np.savez(
        buf,
        **{
            f"p{i}__{k}": np.asarray(v)
            for i, p in enumerate(payloads)
            for k, v in p.items()
        },
    )
    return base64.b64encode(buf.getvalue()).decode()


def decode_payloads(payload: str) -> list[dict]:
    """Inverse of :func:`encode_payloads` — rebuilds the ordered per-leaf
    payload-dict list."""
    out: dict[int, dict] = {}
    with np.load(io.BytesIO(base64.b64decode(payload))) as z:
        for name in z.files:
            head, key = name.split("__", 1)
            out.setdefault(int(head[1:]), {})[key] = np.asarray(z[name])
    return [out[i] for i in range(len(out))]


class AggServer:
    """The commit authority: global leaves + buffer + quorum policy."""

    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 0,
        policy: CommitPolicy | None = None,
        method: str = "mean",
        trim_k: int = 1,
        clip_norm: float = 10.0,
        world: int = 0,
        obs_dir: str | None = None,
        state_dir: str | None = None,
        sketch_seed: int = 0,
    ):
        self.host = host
        self.port = port
        self.policy = policy or CommitPolicy()
        self.method = method
        self.trim_k = trim_k
        self.clip_norm = clip_norm
        self.world = int(world)
        # the shared sketch hash geometry (fed.dcn_sketch_seed): every
        # pushing worker must encode with the SAME seed or the summed
        # sketch decodes garbage
        self.sketch_seed = int(sketch_seed)
        self.obs_dir = obs_dir
        self.state_dir = state_dir
        self.version = 0
        self.global_leaves: list[np.ndarray] | None = None
        # restart incarnation: bumps on every state-restoring start and
        # rides every reply — workers re-hello when they see it change
        self.incarnation = 1
        self.buffer = AggBuffer()
        # push_id -> terminal disposition ({"disposition": ..., ...});
        # an id present here is DONE — re-delivery is a duplicate
        self._push_ledger: dict[str, dict] = {}
        self._ledger_cap = 100_000
        self._dup_pushes = 0
        self.commit_log: list[dict] = []
        self._arrival: dict[str, float] = {}   # pending worker -> arrival time
        self._gate_ms: dict[str, float] = {}   # worker -> LAST commit gate
        self._push_bytes: dict[str, float] = {}  # worker -> wire bytes total
        self._push_counts: dict[str, int] = {}   # worker -> pushes total
        self._push_flows: dict[str, int] = {}    # pending worker -> flow id
        # the last commit's flow id + version: `global` replies attach it
        # so the adopting worker's span can finish the commit's arrow
        self._commit_flow: tuple[int, int] | None = None
        self._workers: set[str] = set()
        self._lock = threading.Lock()
        self._srv: socket.socket | None = None
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        self._instrument()
        self._restore()
        self._g_incarnation.set(float(self.incarnation))

    # --------------------------------------------------------------- obs
    def _instrument(self) -> None:
        from fedrec_tpu.obs import get_registry

        reg = get_registry()
        self._m_commits = reg.counter(
            "agg.commits_total",
            "async global commits the service performed (version bumps)",
        )
        self._m_late = reg.counter(
            "agg.late_folds_total",
            "buffered contributions folded with staleness > 0 "
            "(the straggler path working as designed)",
        )
        self._m_stale = reg.counter(
            "agg.stale_drops_total",
            "buffered contributions dropped past agg.staleness_cap",
        )
        self._g_staleness = reg.gauge(
            "agg.staleness",
            "mean staleness (commits behind) of the last commit's folds",
        )
        self._g_quorum_wait = reg.gauge(
            "agg.quorum_wait_ms",
            "first-arrival -> quorum-close wall time of the last commit "
            "(what the commit actually waited, vs the barrier's full round)",
        )
        self._g_pending = reg.gauge(
            "agg.buffer_pending",
            "contributions sitting in the async buffer right now",
        )
        self._g_gate = reg.gauge(
            "agg.worker_gate_ms",
            "marginal commit delay charged to this worker at its last "
            "commit (the async analogue of critical-path gate_ms; a "
            "straggler that never closes a quorum stays ~0)",
            labels=("worker",),
        )
        self._g_fold = reg.gauge(
            "agg.commit_fold_ms",
            "server-side fold time of the last commit (the 'fold' share "
            "of the queue/wire/fold commit-latency decomposition)",
        )
        self._g_incarnation = reg.gauge(
            "agg.incarnation",
            "this commit authority's restart incarnation (bumps on every "
            "state-restoring start; workers re-hello on a bump)",
        )
        self._m_dups = reg.counter(
            "agg.push_dups_total",
            "duplicate push deliveries dropped by push-id dedup (retries "
            "after a lost ack, chaos duplication) — each acked push folds "
            "at most once",
        )
        self._m_push_bytes = reg.counter(
            "agg.push_bytes_total",
            "contribution wire bytes received per worker (base64 npz as "
            "shipped) — compare codec'd vs dense pushes for the async "
            "uplink saving",
            labels=("worker",),
        )

    def dump_obs(self) -> None:
        if not self.obs_dir:
            return
        from pathlib import Path

        from fedrec_tpu.obs import dump_artifacts, rotate_jsonl

        try:
            rotate_jsonl(Path(self.obs_dir) / "metrics.jsonl", 64.0)
            dump_artifacts(self.obs_dir)
        except OSError:
            pass  # a full disk must not take the commit authority down

    # ------------------------------------------------------- persistence
    _GLOBAL_MAGIC = "fedrec-agg-global-v1"

    def _state_path(self):
        from pathlib import Path

        return Path(self.state_dir) / "agg_buffer.npz" if self.state_dir else None

    def _global_path(self):
        from pathlib import Path

        return Path(self.state_dir) / "agg_global.npz" if self.state_dir else None

    def _persist(self) -> None:
        """Caller holds the lock.  Two sidecars, written at commit/push
        cadence: the pending buffer (``agg_buffer.npz``, pre-existing)
        and the crash-recovery record (``agg_global.npz``: committed
        global leaves + version + incarnation + the push ledger) — what a
        restarted authority resumes from instead of forgetting the run."""
        path = self._state_path()
        if path is None:
            return
        from fedrec_tpu.train.checkpoint import atomic_write_bytes

        try:
            path.parent.mkdir(parents=True, exist_ok=True)
            atomic_write_bytes(path, self.buffer.state_bytes(0, self.version))
            if self.global_leaves is not None:
                meta = {
                    "magic": self._GLOBAL_MAGIC,
                    "version": int(self.version),
                    "incarnation": int(self.incarnation),
                    "num_leaves": len(self.global_leaves),
                    "ledger": self._push_ledger,
                }
                buf = io.BytesIO()
                np.savez(
                    buf,
                    __meta__=np.frombuffer(
                        json.dumps(meta).encode(), np.uint8
                    ),
                    **{
                        f"leaf{i}": np.asarray(x)
                        for i, x in enumerate(self.global_leaves)
                    },
                )
                atomic_write_bytes(self._global_path(), buf.getvalue())
        except OSError:
            pass

    def _restore(self) -> None:
        path = self._state_path()
        if path is not None and path.exists():
            try:
                self.buffer, _, self.version = AggBuffer.load_state(
                    path.read_bytes()
                )
                print(
                    f"[aggserver] restored {len(self.buffer)} pending "
                    f"contribution(s) at version {self.version}",
                    flush=True,
                )
            except (ValueError, OSError) as e:
                print(f"[aggserver] ignoring unreadable buffer sidecar: {e}",
                      flush=True)
        gpath = self._global_path()
        if gpath is None or not gpath.exists():
            return
        try:
            with np.load(io.BytesIO(gpath.read_bytes())) as z:
                meta = json.loads(bytes(z["__meta__"].tobytes()).decode())
                if meta.get("magic") != self._GLOBAL_MAGIC:
                    raise ValueError(
                        f"not an agg-global sidecar "
                        f"(magic={meta.get('magic')!r})"
                    )
                self.global_leaves = [
                    np.asarray(z[f"leaf{i}"])
                    for i in range(int(meta["num_leaves"]))
                ]
            # the global sidecar is written after every commit, so its
            # version is the committed truth; the buffer sidecar rides
            # along and can never be ahead of it
            self.version = max(self.version, int(meta["version"]))
            self.incarnation = int(meta.get("incarnation", 0)) + 1
            ledger = meta.get("ledger") or {}
            if isinstance(ledger, dict):
                self._push_ledger = {
                    str(k): dict(v) for k, v in ledger.items()
                    if isinstance(v, dict)
                }
            print(
                f"[aggserver] resumed committed global v{self.version} as "
                f"incarnation {self.incarnation} "
                f"({len(self._push_ledger)} ledgered push(es))",
                flush=True,
            )
        except (ValueError, OSError, KeyError) as e:
            print(f"[aggserver] ignoring unreadable global sidecar: {e}",
                  flush=True)

    # ----------------------------------------------------------- serving
    def start(self) -> "AggServer":
        srv = socket.create_server((self.host, self.port))
        srv.settimeout(0.5)
        self._srv = srv
        self.port = srv.getsockname()[1]
        t = threading.Thread(target=self._accept_loop, daemon=True)
        t.start()
        self._threads = [t]
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._srv is not None:
            try:
                self._srv.close()
            except OSError:
                pass
        for t in self._threads:
            t.join(timeout=2.0)
        self._persist()
        self.dump_obs()

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    def _accept_loop(self) -> None:
        from fedrec_tpu.obs.fleet import serve_json_line

        assert self._srv is not None
        while not self._stop.is_set():
            try:
                conn, _ = self._srv.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            threading.Thread(
                target=serve_json_line, args=(conn, self.handle),
                kwargs={"timeout_s": 120.0, "recv_bytes": 1 << 22},
                daemon=True,
            ).start()

    # ---------------------------------------------------------- handlers
    def handle(self, req: dict) -> dict:
        cmd = req.get("cmd")
        if cmd == "hello":
            return self._hello(str(req["worker"]), int(req.get("epoch", 0)))
        if cmd == "init":
            return self._init(str(req["worker"]), req["payload"])
        if cmd == "push":
            return self._push(req)
        if cmd == "global":
            return self._global(int(req.get("since", -1)))
        if cmd == "status":
            return self.status()
        return {"error": f"unknown cmd {cmd!r}"}

    def _advertise(self) -> None:
        """Echo the incarnation in the reply ENVELOPE too (additive —
        response dicts carry it as a plain key either way)."""
        if wireobs.current_envelope() is not None:
            wireobs.serve_extra(incarnation=self.incarnation)

    def _ledger_set(self, push_id: str, disposition: str, **kv) -> None:
        """Caller holds the lock.  Record a push id's TERMINAL
        disposition (exactly once per id — re-delivery after this is a
        duplicate).  FIFO-trimmed at ``_ledger_cap``."""
        if not push_id:
            return
        self._push_ledger[push_id] = {"disposition": disposition, **kv}
        if len(self._push_ledger) > self._ledger_cap:
            for k in list(self._push_ledger)[: self._ledger_cap // 2]:
                del self._push_ledger[k]

    def _hello(self, worker: str, epoch: int) -> dict:
        with self._lock:
            self._workers.add(worker)
            world = self.world or len(self._workers)
            if epoch > self.buffer.epoch:
                self.buffer.advance_epoch(epoch)
            self._advertise()
            return {
                "version": self.version,
                "quorum": self.policy.quorum_for(world),
                "have_global": self.global_leaves is not None,
                "incarnation": self.incarnation,
            }

    def _init(self, worker: str, payload: str) -> dict:
        with self._lock:
            if self.global_leaves is None:
                self.global_leaves = decode_leaves(payload)
                print(f"[aggserver] v0 global seeded by {worker!r}", flush=True)
                # the v0 global must survive a pre-first-commit crash
                self._persist()
            self._advertise()
            return {"version": self.version, "incarnation": self.incarnation}

    def _push(self, req: dict) -> dict:
        worker = str(req["worker"])
        codec = str(req.get("codec", "none"))
        push_id = str(req.get("push_id", "") or "")
        with self._lock:
            if self.global_leaves is None:
                return {"error": "push before init: no v0 global"}
            based_on = int(req["based_on"])
            if based_on > self.version:
                # a torn persist can restore the authority a commit
                # behind a worker's adopted version; folding such an
                # entry would ValueError at quorum time and poison every
                # pending worker's commit — reject it at the wire and
                # tell the worker to resync
                return {
                    "error": (
                        f"rebase: push based_on v{based_on} is ahead of "
                        f"the restored global v{self.version} (authority "
                        "restarted); re-hello and adopt the current global"
                    )
                }
            if push_id and push_id in self._push_ledger:
                # idempotent re-delivery of an already-disposed push
                # (retry after a lost ack, chaos duplication): ack it
                # again, never re-buffer — the exactly-once half of the
                # zero-acked-push-loss contract
                self._dup_pushes += 1
                self._m_dups.inc()
                self._advertise()
                return {
                    "version": self.version,
                    "committed": False,
                    "duplicate": True,
                    "incarnation": self.incarnation,
                }
            self._m_push_bytes.inc(
                float(len(req["payload"])), worker=worker
            )
            self._push_bytes[worker] = (
                self._push_bytes.get(worker, 0.0) + float(len(req["payload"]))
            )
            self._push_counts[worker] = self._push_counts.get(worker, 0) + 1
            if codec == "none":
                leaves, entry_codec = decode_leaves(req["payload"]), "none"
            else:
                try:
                    leaves, entry_codec = self._decode_push(
                        codec, req["payload"]
                    )
                except ValueError as e:
                    return {"error": f"bad push codec: {e}"}
            entry = BufferEntry(
                worker=worker,
                round=int(req["round"]),
                epoch=int(req.get("epoch", self.buffer.epoch)),
                based_on=based_on,
                weight=float(req.get("weight", 1.0)),
                arrival_ms=time.monotonic() * 1e3,
                leaves=leaves,
                codec=entry_codec,
                push_id=push_id,
            )
            replaced = self.buffer.add(entry)
            if replaced is not None and replaced.push_id:
                if replaced.push_id == push_id:
                    # the same contribution delivered twice while still
                    # pending (proxy duplication): one entry remains
                    self._dup_pushes += 1
                    self._m_dups.inc()
                else:
                    self._ledger_set(
                        replaced.push_id, "superseded", by=push_id
                    )
            self._workers.add(worker)
            self._arrival[worker] = entry.arrival_ms
            # start a buffer->commit flow arrow inside this push's serve
            # span; the commit that folds this contribution finishes it
            if wireobs.current_envelope() is not None:
                fid = wireobs.new_span_id()
                get_tracer().flow("out", fid, worker=worker)
                self._push_flows[worker] = fid
            committed = self._maybe_commit()
            self._g_pending.set(float(len(self.buffer)))
            self._persist()
            self._advertise()
            return {
                "version": self.version,
                "committed": committed,
                "incarnation": self.incarnation,
            }

    def _decode_push(self, codec: str, payload: str) -> tuple[list, str]:
        """Caller holds the lock.  An encoded push becomes buffer leaves:
        per-contribution codecs densify NOW (decode-at-push — the
        worker-side residual already corrected what the encode drops),
        linear sketches buffer raw and fold in sketch space at commit."""
        validate_codec(codec)
        payloads = decode_payloads(payload)
        assert self.global_leaves is not None
        if len(payloads) != len(self.global_leaves):
            raise ValueError(
                f"push has {len(payloads)} encoded leaves, global has "
                f"{len(self.global_leaves)}"
            )
        if codec_caps(codec).decodes_per_contribution:
            leaves = [
                decode_leaf(
                    p, codec, tuple(np.asarray(g).shape),
                    sketch_seed=self.sketch_seed, leaf_id=j,
                )
                for j, (p, g) in enumerate(
                    zip(payloads, self.global_leaves)
                )
            ]
            return leaves, "none"
        if self.method != "mean":
            # reject at the wire, not inside the commit: a sketch entry
            # under a robust fold would ValueError at quorum time and
            # poison every pending worker's commit
            raise ValueError(
                f"sketch codec {codec!r} cannot fold under robust method "
                f"{self.method!r}; push int8/sign1bit/topk/none instead"
            )
        key = SKETCH_PAYLOAD_KEY[codec]
        return [np.asarray(p[key]) for p in payloads], codec

    def _maybe_commit(self) -> bool:
        """Caller holds the lock.  Fires when quorum-many DISTINCT
        workers are pending; folds EVERYTHING buffered (on-time + late)."""
        world = self.world or max(len(self._workers), 1)
        k = self.policy.quorum_for(world)
        pending = self.buffer.pending_workers()
        if len(pending) < k:
            return False
        entries = self.buffer.take_all()
        assert self.global_leaves is not None
        # ledger every folded/dropped push id BEFORE the version bump
        # (mirrors fold_commit's staleness filter exactly): each acked
        # push reaches exactly one terminal disposition
        for e in entries:
            s = self.version - e.based_on
            self._ledger_set(
                e.push_id,
                "stale_dropped" if s > self.policy.staleness_cap
                else "folded",
                version=self.version + 1,
                staleness=max(s, 0),
            )
        tracer = get_tracer()
        commit_flow = wireobs.new_span_id()
        fold_t0 = time.perf_counter()
        with tracer.span("agg.commit", quorum=len(pending)):
            # finish each folded push's buffer arrow inside the commit
            # span, then start the commit's own arrow (the adopting
            # workers' `global` spans finish it)
            for w in {e.worker for e in entries}:
                fid = self._push_flows.pop(w, None)
                if fid is not None:
                    tracer.flow("in", fid)
            self.global_leaves, stats = fold_commit(
                self.global_leaves, entries, self.version, self.policy,
                method=self.method, trim_k=self.trim_k,
                clip_norm=self.clip_norm, sketch_seed=self.sketch_seed,
            )
            tracer.flow("out", commit_flow, version=stats.version)
        self._g_fold.set((time.perf_counter() - fold_t0) * 1e3)
        self._commit_flow = (stats.version, commit_flow)
        self.version = stats.version
        # gate attribution: the quorum-closing arrival is charged its
        # marginal delay over the runner-up; everyone else 0
        arrivals = sorted(
            (self._arrival[w] for w in pending if w in self._arrival)
        )
        closer = max(
            (w for w in pending if w in self._arrival),
            key=lambda w: self._arrival[w],
        )
        gate = arrivals[-1] - arrivals[-2] if len(arrivals) > 1 else 0.0
        wait = arrivals[-1] - arrivals[0] if len(arrivals) > 1 else 0.0
        for w in pending:
            g = gate if w == closer else 0.0
            self._gate_ms[w] = g
            self._g_gate.set(g, worker=w)
        self._arrival.clear()
        self._m_commits.inc()
        self._m_late.inc(float(stats.late_folds))
        self._m_stale.inc(float(stats.stale_drops))
        self._g_staleness.set(stats.mean_staleness)
        self._g_quorum_wait.set(wait)
        self.commit_log.append(
            {
                "version": stats.version,
                "folded": stats.folded,
                "late_folds": stats.late_folds,
                "stale_drops": stats.stale_drops,
                "mean_staleness": stats.mean_staleness,
                "max_staleness": stats.max_staleness,
                "quorum": len(pending),
                "quorum_wait_ms": wait,
                "closer": closer,
                "gate_ms": gate,
            }
        )
        self.dump_obs()
        return True

    def _global(self, since: int) -> dict:
        with self._lock:
            self._advertise()
            if self.global_leaves is None:
                return {"version": -1, "incarnation": self.incarnation}
            out: dict = {
                "version": self.version,
                "incarnation": self.incarnation,
            }
            if self.version > since:
                out["payload"] = encode_leaves(self.global_leaves)
                if (
                    self._commit_flow is not None
                    and self._commit_flow[0] == self.version
                ):
                    # rides the reply ENVELOPE (wire.last_reply_envelope
                    # on the worker), so the response dict is unchanged
                    wireobs.serve_extra(commit_flow=self._commit_flow[1])
            return out

    def status(self) -> dict:
        with self._lock:
            return {
                "version": self.version,
                "incarnation": self.incarnation,
                "pending": len(self.buffer),
                "pending_workers": sorted(self.buffer.pending_workers()),
                "pending_push_ids": sorted(
                    e.push_id for e in self.buffer.entries if e.push_id
                ),
                "workers": sorted(self._workers),
                "epoch": self.buffer.epoch,
                "commits": list(self.commit_log),
                "gate_ms": dict(self._gate_ms),
                "push_bytes": dict(self._push_bytes),
                "push_counts": dict(self._push_counts),
                "push_dups": self._dup_pushes,
                "ledger": {
                    k: dict(v) for k, v in self._push_ledger.items()
                },
            }


def main(argv: list[str] | None = None) -> int:
    """Standalone commit authority (the async smoke's control plane)."""
    import argparse

    parser = argparse.ArgumentParser(
        description="fedrec buffered-aggregation (async commit) service"
    )
    parser.add_argument("address", metavar="HOST:PORT")
    parser.add_argument("--quorum", type=int, default=0,
                        help="commit once this many distinct workers are "
                             "pending (agg.quorum; 0 = all-reporting)")
    parser.add_argument("--staleness-cap", type=int, default=2,
                        help="drop buffered updates older than this many "
                             "commits (agg.staleness_cap)")
    parser.add_argument("--world", type=int, default=0,
                        help="expected worker count (0 = learn from hellos)")
    parser.add_argument("--method", default="mean",
                        help="fed.robust.method applied to the delta fold")
    parser.add_argument("--obs-dir", default=None,
                        help="write the service's obs artifact trio here — "
                             "name it worker_aggserver under the fleet obs "
                             "root so fedrec-obs fleet merges the commit/"
                             "gate story")
    parser.add_argument("--state-dir", default=None,
                        help="persist the pending buffer here across "
                             "restarts (agg_buffer.npz)")
    parser.add_argument("--sketch-seed", type=int, default=0,
                        help="shared sketch hash seed (fed.dcn_sketch_seed) "
                             "for decoding sketch-coded pushes — must match "
                             "every worker's")
    parser.add_argument("--slo", default="",
                        help="obs.slo.objectives spec evaluated at status "
                             "cadence against this service's own registry "
                             "(agg.quorum_wait_ms / agg.staleness / "
                             "agg.buffer_pending ...); alert records land "
                             "in --obs-dir's metrics.jsonl")
    args = parser.parse_args(argv)
    host, port = args.address.rsplit(":", 1)
    if args.obs_dir:
        from fedrec_tpu.obs.fleet import set_fleet_identity

        set_fleet_identity(worker="aggserver")
    server = AggServer(
        host=host, port=int(port),
        policy=CommitPolicy(quorum=args.quorum,
                            staleness_cap=args.staleness_cap),
        method=args.method, world=args.world,
        obs_dir=args.obs_dir, state_dir=args.state_dir,
        sketch_seed=args.sketch_seed,
    ).start()
    print(f"[aggserver] serving on {server.address}", flush=True)
    watch = None
    if args.slo:
        from pathlib import Path

        from fedrec_tpu.config import SloConfig, WatchConfig
        from fedrec_tpu.obs.watch import Watch

        if args.obs_dir:
            Path(args.obs_dir).mkdir(parents=True, exist_ok=True)
        watch = Watch(
            SloConfig(enabled=True, objectives=args.slo),
            WatchConfig(),
            jsonl_path=(
                Path(args.obs_dir) / "metrics.jsonl"
                if args.obs_dir else None
            ),
        )

    import signal

    def _term(signum, frame):  # noqa: ARG001 — signal handler signature
        raise SystemExit(0)

    try:
        signal.signal(signal.SIGTERM, _term)
    except (ValueError, OSError):
        pass  # not the main thread / unsupported platform: best effort
    try:
        last = None
        while True:
            time.sleep(2)
            if watch is not None:
                watch.evaluate()  # commit-cadence SLOs over agg.* gauges
            status = server.status() if args.obs_dir else None
            if args.obs_dir and status != last:
                server.dump_obs()
                last = status
    except (KeyboardInterrupt, SystemExit):
        pass
    finally:
        server.stop()
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
