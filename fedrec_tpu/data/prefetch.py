"""Bounded double-buffered host prefetch for the training input pipeline.

The train loop's host work — epoch shuffling, negative sampling, batch
packing (``TrainBatcher``/``NativeTrainBatcher``), and optionally the
host→device ``device_put`` — runs serially with the device step when the
loop is written naively: the device sits idle for the whole batch-build
time between dispatches (the "dispatch gap" row in
``benchmarks/step_profile.py``). :class:`Prefetcher` moves that work onto
a producer thread with a BOUNDED handoff queue, so batch t+1 is built
while step t runs; the bound (``data.prefetch_batches``, 2 = classic
double buffering) keeps host memory flat instead of racing ahead of the
device by a whole epoch.

Guarantees (pinned in ``tests/test_prefetch.py``):

  * **Determinism** — one producer thread consumes the source iterator in
    order into a FIFO queue: the consumer sees exactly the batches, in
    exactly the order, the bare iterator would yield. Prefetch is a
    scheduling change, never a data change.
  * **Bounded depth** — the producer blocks once ``depth`` items are
    queued; a slow consumer can never make the producer buffer the epoch.
  * **Clean shutdown** — a producer-side exception is re-raised in the
    consumer at the position the failed item would have occupied (not
    swallowed, not deferred to join); closing mid-epoch (``close()``,
    ``with``, or generator ``.close()``) unblocks and joins the producer
    thread without leaking it.

The producer holds no JAX state; when a ``transform`` is given (e.g. the
Trainer's dict packaging) it runs on the producer thread too, off the
dispatch path.

Queue health is first-class telemetry (:mod:`fedrec_tpu.obs`): a
``data.prefetch.queue_depth`` gauge plus producer-stall (queue full —
the device is the bottleneck, good) and consumer-stall (queue empty —
batch build is the bottleneck, the dispatch gap is back) counters, so
"is prefetch actually hiding the host work?" is answerable from a
registry snapshot instead of a profiler session.
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Callable, Iterable, Iterator

from fedrec_tpu.obs import get_registry


class _Stop:
    """Sentinel: source iterator exhausted."""


class _Raised:
    """Sentinel: producer raised; carries the exception to the consumer."""

    def __init__(self, exc: BaseException):
        self.exc = exc


class Prefetcher:
    """Iterate ``source`` through a bounded background queue.

    ``depth``: max items built ahead of the consumer (>= 1).
    ``transform``: optional per-item callable applied on the producer
    thread (host-side packaging/transfer work to overlap with the step).
    """

    def __init__(
        self,
        source: Iterable,
        depth: int,
        transform: Callable[[Any], Any] | None = None,
        registry=None,
    ):
        if depth < 1:
            raise ValueError(f"prefetch depth must be >= 1, got {depth}")
        self._q: queue.Queue = queue.Queue(maxsize=depth)
        self._stop = threading.Event()
        self._source = iter(source)
        self._transform = transform
        reg = registry or get_registry()
        self._depth_gauge = reg.gauge(
            "data.prefetch.queue_depth", "batches ready in the handoff queue"
        )
        self._producer_stalls = reg.counter(
            "data.prefetch.producer_stall_total",
            "items that waited on a full queue (device is the bottleneck)",
        )
        self._consumer_stalls = reg.counter(
            "data.prefetch.consumer_stall_total",
            "consumer reads that found the queue empty (batch build is the bottleneck)",
        )
        self._items = reg.counter(
            "data.prefetch.items_total", "batches delivered through the prefetcher"
        )
        self._thread = threading.Thread(
            target=self._produce, name="fedrec-prefetch", daemon=True
        )
        self._thread.start()

    # ------------------------------------------------------------- producer
    def _put(self, item: Any) -> bool:
        """Blocking put that stays responsive to close(): returns False when
        the consumer has gone away (item dropped, producer should exit)."""
        if self._q.full():
            # the producer is about to wait on the consumer — the healthy
            # direction (device-bound); counted at put-entry because the
            # timed put below masks sub-timeout waits
            self._producer_stalls.inc()
        while not self._stop.is_set():
            try:
                self._q.put(item, timeout=0.1)
                self._depth_gauge.set(self._q.qsize())
                return True
            except queue.Full:
                continue
        return False

    def _produce(self) -> None:
        try:
            for item in self._source:
                if self._transform is not None:
                    item = self._transform(item)
                if not self._put(item):
                    return
                if self._stop.is_set():
                    return
            self._put(_Stop)
        except BaseException as e:  # noqa: BLE001 — relayed, not swallowed
            self._put(_Raised(e))

    # ------------------------------------------------------------- consumer
    def __iter__(self) -> Iterator:
        try:
            while True:
                if self._q.empty():
                    # the step is about to wait on batch build — the exact
                    # dispatch-gap signal the prefetcher exists to remove
                    self._consumer_stalls.inc()
                item = self._q.get()
                self._depth_gauge.set(self._q.qsize())
                if item is _Stop:
                    return
                if isinstance(item, _Raised):
                    raise item.exc
                self._items.inc()
                yield item
        finally:
            # reached on StopIteration, consumer break, generator .close(),
            # and consumer-side exceptions alike
            self.close()

    def close(self) -> None:
        """Stop the producer and join its thread; idempotent."""
        self._stop.set()
        # unblock a producer stuck in put() on a full queue
        try:
            while True:
                self._q.get_nowait()
        except queue.Empty:
            pass
        self._thread.join(timeout=5.0)

    def __enter__(self) -> "Prefetcher":
        return self

    def __exit__(self, *exc_info) -> None:
        self.close()


def maybe_prefetch(
    source: Iterable,
    depth: int,
    transform: Callable[[Any], Any] | None = None,
) -> Iterable:
    """``Prefetcher`` when ``depth`` > 0, else the bare iterable (with
    ``transform`` applied inline, so callers get one code path)."""
    if depth > 0:
        return Prefetcher(source, depth, transform)
    if transform is None:
        return source
    return (transform(item) for item in source)
