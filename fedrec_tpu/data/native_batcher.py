"""ctypes binding for the native C++ data engine (``native/fedrec_data.cpp``).

``NativeTrainBatcher`` is a drop-in replacement for
``fedrec_tpu.data.batcher.TrainBatcher`` whose host-side hot loop — epoch
shuffling, round-robin client sharding, negative sampling, batch packing —
runs in the C++ library (threaded for whole-epoch fills). This is the
TPU-native equivalent of the reference's torch ``DataLoader`` workers
(reference ``dataset.py:69-86``, ``main.py:166``): the reference's native
loading lives inside the torch wheel; ours is a first-class framework
component.

Shapes, sharding, padding, and pool-shorter-than-ratio semantics match the
Python batcher exactly; the negative-sampling RNG is the engine's own
deterministic per-(seed, epoch, client, batch) stream, so draws are
reproducible but not bit-identical to numpy's.

The shared library is loaded from ``native/libfedrec_data.so``; if missing,
``ensure_built()`` compiles it with ``make`` (g++ is part of the toolchain).
``is_available()`` gates use so pure-Python environments keep working.
"""

from __future__ import annotations

import ctypes
import subprocess
from pathlib import Path
from typing import Iterator

import numpy as np

from fedrec_tpu.data.batcher import Batch, IndexedSamples

_NATIVE_DIR = Path(__file__).resolve().parent.parent.parent / "native"
_LIB_PATH = _NATIVE_DIR / "libfedrec_data.so"

_lib: ctypes.CDLL | None = None
_load_error: str | None = None


def ensure_built() -> bool:
    """Build the shared library if missing. Returns True when present.

    A failed build is cached (``_load_error``) so repeated availability
    probes don't re-spawn ``make`` each time.
    """
    global _load_error
    if _LIB_PATH.exists():
        return True
    if _load_error is not None:
        return False
    if not (_NATIVE_DIR / "Makefile").exists():
        _load_error = f"{_NATIVE_DIR}/Makefile missing"
        return False
    try:
        subprocess.run(
            ["make", "-C", str(_NATIVE_DIR)],
            check=True,
            capture_output=True,
            timeout=120,
        )
    except (subprocess.SubprocessError, OSError) as e:
        _load_error = f"native build failed: {e}"
        return False
    if not _LIB_PATH.exists():
        _load_error = f"build succeeded but {_LIB_PATH} missing"
        return False
    return True


def _load() -> ctypes.CDLL | None:
    global _lib, _load_error
    if _lib is not None:
        return _lib
    if _load_error is not None:
        return None
    if not ensure_built():
        return None
    try:
        lib = ctypes.CDLL(str(_LIB_PATH))
    except OSError as e:  # pragma: no cover - host-specific
        _load_error = str(e)
        return None

    i32p = ctypes.POINTER(ctypes.c_int32)
    lib.frd_create.restype = ctypes.c_void_p
    lib.frd_create.argtypes = [
        i32p, i32p, i32p, i32p, i32p,
        ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
        ctypes.c_int64, ctypes.c_int64,
        ctypes.c_int, ctypes.c_int, ctypes.c_uint64,
    ]
    lib.frd_destroy.restype = None
    lib.frd_destroy.argtypes = [ctypes.c_void_p]
    lib.frd_num_batches.restype = ctypes.c_int64
    lib.frd_num_batches.argtypes = [ctypes.c_void_p, ctypes.c_int64]
    lib.frd_fill_batch.restype = ctypes.c_int
    lib.frd_fill_batch.argtypes = [
        ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
        i32p, i32p, i32p, i32p,
    ]
    lib.frd_fill_epoch.restype = ctypes.c_int
    lib.frd_fill_epoch.argtypes = [
        ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
        i32p, i32p, i32p, i32p,
    ]
    _lib = lib
    return _lib


def is_available() -> bool:
    return _load() is not None


def _ptr(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))


class NativeTrainBatcher:
    """TrainBatcher-compatible façade over the C++ engine."""

    def __init__(
        self,
        indexed: IndexedSamples,
        batch_size: int,
        npratio: int = 4,
        shuffle: bool = True,
        drop_remainder: bool = True,
        seed: int = 0,
        num_threads: int = 0,
    ):
        lib = _load()
        if lib is None:
            raise RuntimeError(f"native data engine unavailable: {_load_error}")
        self._lib = lib
        self.batch_size = batch_size
        self.npratio = npratio
        self.num_threads = num_threads
        self.max_his = indexed.history.shape[1]
        self.drop_remainder = drop_remainder
        self._n = len(indexed)

        pos = np.ascontiguousarray(indexed.pos, dtype=np.int32)
        pools = np.ascontiguousarray(indexed.neg_pools, dtype=np.int32)
        lens = np.ascontiguousarray(indexed.neg_lens, dtype=np.int32)
        hist = np.ascontiguousarray(indexed.history, dtype=np.int32)
        hlen = np.ascontiguousarray(indexed.his_len, dtype=np.int32)
        self._handle = lib.frd_create(
            _ptr(pos), _ptr(pools), _ptr(lens), _ptr(hist), _ptr(hlen),
            len(indexed), pools.shape[1], self.max_his,
            batch_size, npratio, int(shuffle), int(drop_remainder),
            ctypes.c_uint64(seed & 0xFFFFFFFFFFFFFFFF).value,
        )
        if not self._handle:
            raise RuntimeError("frd_create rejected the arguments")

    def __del__(self):
        handle = getattr(self, "_handle", None)
        if handle:
            self._lib.frd_destroy(handle)
            self._handle = None

    # ------------------------------------------------------------------
    def num_batches(self, n: int | None = None) -> int:
        """Batches per epoch for ``n`` samples (TrainBatcher contract:
        the argument is a SAMPLE count, defaulting to the dataset size)."""
        n = self._n if n is None else n
        if self.drop_remainder:
            return n // self.batch_size
        return -(-n // self.batch_size)

    def _steps(self, num_clients: int) -> int:
        """Steps per epoch when dealt round-robin over ``num_clients``."""
        return int(self._lib.frd_num_batches(self._handle, num_clients))

    def _alloc(self, num_clients: int, steps: int | None = None):
        lead = () if steps is None else (steps,)
        b, c, h = self.batch_size, 1 + self.npratio, self.max_his
        return (
            np.empty((*lead, num_clients, b, c), np.int32),
            np.empty((*lead, num_clients, b, h), np.int32),
            np.empty((*lead, num_clients, b), np.int32),
            np.empty((*lead, num_clients, b), np.int32),
        )

    def _fill_batch(self, epoch: int, idx: int, num_clients: int) -> Batch:
        cand, hist, hlen, labels = self._alloc(num_clients)
        rc = self._lib.frd_fill_batch(
            self._handle, epoch, idx, num_clients,
            _ptr(cand), _ptr(hist), _ptr(hlen), _ptr(labels),
        )
        if rc != 0:
            raise ValueError(f"frd_fill_batch failed (rc={rc})")
        return Batch(cand, hist, hlen, labels)

    # ------------------------------------------------------------------
    def epoch_batches(self, epoch: int = 0) -> Iterator[Batch]:
        for i in range(self._steps(1)):
            b = self._fill_batch(epoch, i, 1)
            yield Batch(b.candidates[0], b.history[0], b.his_len[0], b.labels[0])

    def epoch_batches_sharded(
        self, num_clients: int, epoch: int = 0
    ) -> Iterator[Batch]:
        for i in range(self._steps(num_clients)):
            yield self._fill_batch(epoch, i, num_clients)

    def epoch_arrays_sharded(self, num_clients: int, epoch: int = 0) -> Batch:
        """Whole epoch (steps, C, B, ...) filled by the threaded native path."""
        steps = self._steps(num_clients)
        if steps == 0:
            raise ValueError(
                "no batches: dataset smaller than num_clients*batch_size"
            )
        cand, hist, hlen, labels = self._alloc(num_clients, steps)
        rc = self._lib.frd_fill_epoch(
            self._handle, epoch, num_clients, self.num_threads,
            _ptr(cand), _ptr(hist), _ptr(hlen), _ptr(labels),
        )
        if rc != 0:
            raise ValueError(f"frd_fill_epoch failed (rc={rc})")
        return Batch(cand, hist, hlen, labels)
