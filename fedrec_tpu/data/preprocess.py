"""MIND raw-tsv -> training artifacts — the pipeline absent from the reference.

The reference repo ships only the *outputs* of its (unpublished) preprocessing
(``UserData/bert_news_index.npy``, ``bert_nid2index.pkl``,
``train_sam_uid.pkl``, ``valid_sam_uid.pkl`` — formats documented at
SURVEY.md section 2.1 / reference ``main.py:148-157``). This module rebuilds
the pipeline from the documented formats against the public MIND tsv layout:

  * ``news.tsv``     — ``nid \t category \t subcategory \t title \t abstract
    \t url \t title_entities \t abstract_entities``
  * ``behaviors.tsv`` — ``impression_id \t user_id \t time \t history
    \t impressions`` where impressions are ``Nxxxx-1`` (clicked) /
    ``Nxxxx-0`` (shown, not clicked)

Artifact semantics (kept bit-compatible with the loader,
``fedrec_tpu.data.mind``):

  * news index row 0 is ``<unk>`` (all-zero tokens), ``nid2index['<unk>']==0``
  * one sample per CLICK — for train AND valid — of the form
    ``[uidx, pos_nid, neg_pool, history, uid]`` with the impression's
    non-clicked candidates as the negative pool (``npratio`` negatives are
    drawn per epoch at batch time, reference ``dataset.py:79-86``). The
    shipped valid artifact uses the same single-pos layout, and the reference
    validator unpacks ``sample[1]`` as one nid (``client.py:160``); a
    multi-click impression therefore yields one validation sample per click.
  * clicks with an empty negative pool are kept (the sampler pads with
    ``<unk>``, reference ``dataset.py:11-12``)

Usage:
  python -m fedrec_tpu.data.preprocess --news news.tsv \
      --train-behaviors train/behaviors.tsv --valid-behaviors dev/behaviors.tsv \
      --out-dir UserData [--vocab vocab.txt] [--max-title-len 50]
"""

from __future__ import annotations

import argparse
import pickle
from pathlib import Path

import numpy as np

from fedrec_tpu.data.mind import MindData
from fedrec_tpu.data.tokenizer import get_tokenizer


def parse_news_tsv(path: str | Path) -> dict[str, str]:
    """-> ordered ``{nid: title}``; first field wins on duplicate nids."""
    titles: dict[str, str] = {}
    with open(path, encoding="utf-8") as f:
        for line in f:
            parts = line.rstrip("\n").split("\t")
            if len(parts) < 4:
                continue
            nid, title = parts[0], parts[3]
            if nid and nid not in titles:
                titles[nid] = title
    return titles


def build_news_index(
    titles: dict[str, str], tokenizer, max_title_len: int = 50
) -> tuple[np.ndarray, dict[str, int]]:
    """-> ((N+1, 2, L) int64 tokens+mask, nid2index with ``<unk> -> 0``)."""
    nid2index = {"<unk>": 0}
    rows = [np.zeros((2, max_title_len), np.int64)]  # row 0 = <unk>
    for nid, title in titles.items():
        ids, mask = tokenizer.encode(title, max_title_len)
        nid2index[nid] = len(rows)
        rows.append(np.stack([ids, mask]))
    return np.stack(rows), nid2index


def parse_behaviors_tsv(
    path: str | Path,
    known_nids: set[str],
    max_his_len: int | None = None,
    uid2idx: dict[str, int] | None = None,
) -> list:
    """behaviors.tsv -> ``[uidx, pos, neg_pool, history, uid]`` per click.

    Unknown nids (not in ``news.tsv``) are dropped from histories and pools;
    a click on an unknown nid is skipped entirely. ``max_his_len`` optionally
    pre-truncates histories to the most recent clicks (the batcher truncates
    again regardless — ledger note at ``fedrec_tpu.data.batcher``). Pass one
    shared ``uid2idx`` across train/valid calls so a given uidx means the
    same user in both artifacts.
    """
    samples: list = []
    if uid2idx is None:
        uid2idx = {}
    with open(path, encoding="utf-8") as f:
        for line in f:
            parts = line.rstrip("\n").split("\t")
            if len(parts) < 5:
                continue
            _, uid, _time, history_s, impressions_s = parts[:5]
            if uid not in uid2idx:
                uid2idx[uid] = len(uid2idx)
            uidx = uid2idx[uid]
            history = [n for n in history_s.split() if n in known_nids]
            if max_his_len is not None:
                history = history[-max_his_len:]
            clicked, pool = [], []
            for item in impressions_s.split():
                nid, _, label = item.rpartition("-")
                if not nid or nid not in known_nids:
                    continue
                (clicked if label == "1" else pool).append(nid)
            for pos in clicked:
                samples.append([uidx, pos, list(pool), list(history), uid])
    return samples


def preprocess_mind(
    news_path: str | Path,
    train_behaviors: str | Path,
    valid_behaviors: str | Path | None = None,
    out_dir: str | Path | None = None,
    vocab_path: str | Path | None = None,
    max_title_len: int = 50,
) -> MindData:
    """Full pipeline; writes the four reference-format artifacts if
    ``out_dir`` is given and always returns the in-memory ``MindData``."""
    tokenizer = get_tokenizer(vocab_path)
    titles = parse_news_tsv(news_path)
    news_tokens, nid2index = build_news_index(titles, tokenizer, max_title_len)
    known = set(titles)
    uid2idx: dict[str, int] = {}  # shared: uidx must mean one user across splits
    train_samples = parse_behaviors_tsv(train_behaviors, known, uid2idx=uid2idx)
    valid_samples = (
        parse_behaviors_tsv(valid_behaviors, known, uid2idx=uid2idx)
        if valid_behaviors
        else []
    )
    data = MindData(news_tokens, nid2index, train_samples, valid_samples)
    if out_dir is not None:
        write_artifacts(data, out_dir)
    return data


def write_artifacts(data: MindData, out_dir: str | Path) -> None:
    out = Path(out_dir)
    out.mkdir(parents=True, exist_ok=True)
    np.save(out / "bert_news_index.npy", data.news_tokens)
    with open(out / "bert_nid2index.pkl", "wb") as f:
        pickle.dump(data.nid2index, f)
    with open(out / "train_sam_uid.pkl", "wb") as f:
        pickle.dump(data.train_samples, f)
    with open(out / "valid_sam_uid.pkl", "wb") as f:
        pickle.dump(data.valid_samples, f)


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--news", required=True)
    p.add_argument("--train-behaviors", required=True)
    p.add_argument("--valid-behaviors", default=None)
    p.add_argument("--out-dir", required=True)
    p.add_argument("--vocab", default=None, help="BERT vocab.txt (WordPiece); "
                   "omitted -> deterministic hashing tokenizer")
    p.add_argument("--max-title-len", type=int, default=50)
    args = p.parse_args(argv)
    data = preprocess_mind(
        args.news, args.train_behaviors, args.valid_behaviors,
        args.out_dir, args.vocab, args.max_title_len,
    )
    print(
        f"wrote {args.out_dir}: {data.num_news} news, "
        f"{len(data.train_samples)} train / {len(data.valid_samples)} valid samples"
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
