"""Adressa event-log -> training artifacts (second dataset family).

The reference publishes Adressa headline numbers (AUC 72.04, reference
``README.md:76-80``) but — as with MIND — ships no preprocessing code. This
adapter rebuilds the capability for the public Adressa format: JSON-lines
event logs (one JSON object per pageview) from Adresseavisen, fields of
interest being ``userId``, ``id``/``documentId`` (news id), ``title``, and
``time`` (unix seconds).

Pipeline (the standard construction used by news-rec work on Adressa, mapped
onto the reference's artifact schema so everything downstream —
``index_samples``, ``TrainBatcher``, ``Trainer`` — is shared with MIND):

  1. collect each user's clicks, time-sorted; dedupe news by id
  2. per click: history = that user's earlier clicks; negatives = a random
     corpus sample excluding the user's own clicks (Adressa logs have no
     shown-but-not-clicked impressions, so the negative pool is sampled —
     documented divergence from MIND's impression pools)
  3. chronological split: the last ``valid_frac`` of each user's clicks form
     the validation samples
  4. artifacts written in the exact ``UserData/`` schema
     (``[uidx, pos, neg_pool, history, uid]``; news table ``(N, 2, L)``)

Usage:
  python -m fedrec_tpu.data.adressa --events one_week/2017010* \
      --out-dir AdressaData [--vocab vocab.txt] [--max-title-len 30]
"""

from __future__ import annotations

import argparse
import json
from pathlib import Path

import numpy as np

from fedrec_tpu.data.mind import MindData
from fedrec_tpu.data.preprocess import build_news_index, write_artifacts
from fedrec_tpu.data.tokenizer import get_tokenizer


def parse_adressa_events(
    paths: list[str | Path],
) -> tuple[dict[str, str], dict[str, list[tuple[int, str]]]]:
    """JSON-lines event files -> (``{nid: title}``, ``{uid: [(time, nid)]}``).

    Events without a news id, title, or user are skipped (the raw logs mix
    pageviews of front pages and ads with article reads). Repeated clicks by
    the same user on the same article keep only the earliest timestamp, so
    the clicks mapping is independent of the order event files are passed
    (titles keep the first-seen text per nid, which does depend on order
    when a dump revises a title).
    """
    titles: dict[str, str] = {}
    seen: dict[tuple[str, str], int] = {}  # (uid, nid) -> earliest click time
    for path in paths:
        with open(path, encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    ev = json.loads(line)
                except json.JSONDecodeError:
                    continue
                nid = ev.get("id") or ev.get("documentId")
                title = ev.get("title")
                uid = ev.get("userId")
                t = ev.get("time")
                if isinstance(t, str):  # some dumps carry numeric strings
                    try:
                        t = float(t)
                    except ValueError:
                        t = None
                # timeless events are skipped: a fabricated time=0 would sort
                # the click to the front and corrupt the chronological
                # history/validation split
                if not nid or not title or not uid or not isinstance(t, (int, float)):
                    continue
                titles.setdefault(nid, title)
                # dedupe repeat clicks by keeping the EARLIEST timestamp so
                # chronological histories don't depend on file-read order
                key = (uid, nid)
                prev = seen.get(key)
                if prev is None or int(t) < prev:
                    seen[key] = int(t)
    clicks: dict[str, list[tuple[int, str]]] = {}
    for (uid, nid), t in seen.items():
        clicks.setdefault(uid, []).append((t, nid))
    for uid in clicks:
        clicks[uid].sort()
    return titles, clicks


def build_adressa_samples(
    titles: dict[str, str],
    clicks: dict[str, list[tuple[int, str]]],
    min_history: int = 1,
    neg_pool_size: int = 20,
    valid_frac: float = 0.1,
    seed: int = 0,
) -> tuple[list, list]:
    """-> (train_samples, valid_samples) in the reference schema.

    Per user, clicks after the first ``min_history`` become samples; the last
    ``ceil(valid_frac * n_samples)`` (chronologically) go to validation.
    """
    rng = np.random.default_rng(seed)
    all_nids = list(titles)
    train, valid = [], []
    for uidx, (uid, events) in enumerate(sorted(clicks.items())):
        nids = [nid for _, nid in events]
        if len(nids) <= min_history:
            continue
        clicked = set(nids)
        n_eligible = len(all_nids) - len(clicked)

        def draw_pool() -> list[str]:
            # rejection-sample indices against the (small) clicked set; exact
            # per-user eligible-list materialization would be O(users x corpus)
            k = min(neg_pool_size, n_eligible)
            pool: list[str] = []
            chosen: set[str] = set()
            # typical case: clicked << corpus, a couple of rounds suffice
            for _ in range(8):
                for j in rng.integers(0, len(all_nids), size=4 * k):
                    n = all_nids[j]
                    if n not in clicked and n not in chosen:
                        pool.append(n)
                        chosen.add(n)
                        if len(pool) == k:
                            return pool
            # heavy reader (clicked ~ corpus): fall back to the exact filter
            eligible = [n for n in all_nids if n not in clicked and n not in chosen]
            take = rng.choice(len(eligible), size=k - len(pool), replace=False)
            return pool + [eligible[int(i)] for i in take]

        n_samples = len(nids) - min_history
        # keep at least one train sample per user: a ceil-only split would
        # banish every 2-click user's single sample to validation
        n_valid = (
            min(n_samples - 1, int(np.ceil(valid_frac * n_samples)))
            if valid_frac > 0
            else 0
        )
        for i in range(min_history, len(nids)):
            pos, history = nids[i], nids[:i]
            sample = [uidx, pos, draw_pool(), history, uid]
            (valid if i >= len(nids) - n_valid else train).append(sample)
    return train, valid


def make_synthetic_adressa_events(
    num_users: int = 2_000,
    num_news: int = 1_500,
    num_topics: int = 12,
    topics_per_user: int = 2,
    p_pref: float = 0.9,
    clicks_range: tuple[int, int] = (4, 30),
    title_words: tuple[int, int] = (5, 9),
    words_per_topic: int = 12,
    p_topic_word: float = 0.85,
    seed: int = 0,
) -> list[dict]:
    """Synthetic Adressa-format event log with a recoverable topic signal.

    The lexical twin of ``make_synthetic_mind_topics``: every news item
    belongs to a latent topic whose TITLES share a topic vocabulary (each
    title word is topical w.p. ``p_topic_word``, else from a common pool),
    and every user clicks preferred-topic articles w.p. ``p_pref``. Because
    the signal lives in the *words*, it survives the real pipeline —
    tokenizer, ``build_news_index``, chronological splits — so an accuracy
    run through :func:`preprocess_adressa` trains on exactly what a real
    Adressa dump would exercise. Click timestamps increase per user; the
    adapter's chronological validation split therefore holds out each
    user's latest clicks.

    Returns a list of event dicts (``userId``/``id``/``title``/``time``)
    ready to be written as JSON-lines.
    """
    rng = np.random.default_rng(seed)
    # every topic must own >=1 news or the preferred-topic sampler crashes
    # (same guard as make_synthetic_mind_topics): clamp the topic count to
    # the corpus, then assign round-robin-then-shuffle so no topic is empty
    num_topics = min(num_topics, num_news)
    topics_per_user = min(topics_per_user, num_topics)
    topic_of = rng.permutation(np.arange(num_news) % num_topics)
    common = [f"felles{j}" for j in range(200)]

    def title_for(n: int) -> str:
        t = topic_of[n]
        k = int(rng.integers(*title_words, endpoint=True))
        words = [
            f"emne{t}ord{rng.integers(0, words_per_topic)}"
            if rng.random() < p_topic_word
            else common[rng.integers(0, len(common))]
            for _ in range(k)
        ]
        return " ".join(words)

    titles = [title_for(n) for n in range(num_news)]
    by_topic = [np.flatnonzero(topic_of == t) for t in range(num_topics)]

    events: list[dict] = []
    for u in range(num_users):
        pref = rng.choice(num_topics, size=topics_per_user, replace=False)
        n_clicks = int(rng.integers(*clicks_range, endpoint=True))
        t0 = int(rng.integers(1_500_000_000, 1_510_000_000))
        seen: set[int] = set()
        for c in range(n_clicks):
            if rng.random() < p_pref:
                t = int(pref[rng.integers(0, topics_per_user)])
                n = int(by_topic[t][rng.integers(0, len(by_topic[t]))])
            else:
                n = int(rng.integers(0, num_news))
            if n in seen:  # the adapter dedupes repeat clicks anyway
                continue
            seen.add(n)
            events.append(
                {
                    "userId": f"u{u:06d}",
                    "id": f"adr{n}",
                    "title": titles[n],
                    "time": t0 + 60 * c,
                }
            )
    return events


def preprocess_adressa(
    event_paths: list[str | Path],
    out_dir: str | Path | None = None,
    vocab_path: str | Path | None = None,
    max_title_len: int = 30,
    min_history: int = 1,
    neg_pool_size: int = 20,
    valid_frac: float = 0.1,
    seed: int = 0,
) -> MindData:
    tokenizer = get_tokenizer(vocab_path)
    titles, clicks = parse_adressa_events(event_paths)
    news_tokens, nid2index = build_news_index(titles, tokenizer, max_title_len)
    train, valid = build_adressa_samples(
        titles, clicks, min_history, neg_pool_size, valid_frac, seed
    )
    data = MindData(news_tokens, nid2index, train, valid)
    if out_dir is not None:
        write_artifacts(data, out_dir)
    return data


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--events", nargs="+", required=True, help="event JSON-lines files")
    p.add_argument("--out-dir", required=True)
    p.add_argument("--vocab", default=None)
    p.add_argument("--max-title-len", type=int, default=30)
    p.add_argument("--min-history", type=int, default=1)
    p.add_argument("--neg-pool-size", type=int, default=20)
    p.add_argument("--valid-frac", type=float, default=0.1)
    p.add_argument("--seed", type=int, default=0)
    args = p.parse_args(argv)
    data = preprocess_adressa(
        args.events, args.out_dir, args.vocab, args.max_title_len,
        args.min_history, args.neg_pool_size, args.valid_frac, args.seed,
    )
    print(
        f"wrote {args.out_dir}: {data.num_news} news, "
        f"{len(data.train_samples)} train / {len(data.valid_samples)} valid samples"
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
