"""Offline tokenizers producing the ``bert_news_index`` artifact format.

The reference ships pre-tokenized artifacts (``UserData/bert_news_index.npy``:
int64 ``(N, 2, L)`` = stacked [token_ids; attention_mask]) but NOT the
pipeline that produced them (SURVEY.md section 7, hard part (e)). This module
rebuilds that capability without network access:

  * ``WordPieceTokenizer`` — BERT-uncased-compatible: basic tokenization
    (lowercase, accent-strip, punctuation split) + greedy longest-match
    WordPiece against a ``vocab.txt``. Point it at a local
    ``bert-base-uncased``/``distilbert-base-uncased`` vocab file and the ids
    match HF's tokenizer for standard text.
  * ``HashingTokenizer`` — deterministic fallback when no vocab file exists
    (zero-egress environments): whitespace+punct words hashed into the vocab
    range. Unsuitable for pretrained-weight runs, fine for from-scratch
    training and smoke tests.
"""

from __future__ import annotations

import hashlib
import unicodedata
from pathlib import Path

import numpy as np

# BERT special token ids (bert-base-uncased vocab layout)
PAD_ID, UNK_ID, CLS_ID, SEP_ID, MASK_ID = 0, 100, 101, 102, 103


def _is_punctuation(ch: str) -> bool:
    cp = ord(ch)
    if 33 <= cp <= 47 or 58 <= cp <= 64 or 91 <= cp <= 96 or 123 <= cp <= 126:
        return True
    return unicodedata.category(ch).startswith("P")


def basic_tokenize(text: str, lowercase: bool = True) -> list[str]:
    """Whitespace + punctuation splitting with accent stripping (BERT basic)."""
    if lowercase:
        text = text.lower()
    text = unicodedata.normalize("NFD", text)
    out: list[str] = []
    word: list[str] = []
    for ch in text:
        if unicodedata.category(ch) == "Mn":  # strip accents
            continue
        if ch.isspace():
            if word:
                out.append("".join(word))
                word = []
        elif _is_punctuation(ch):
            if word:
                out.append("".join(word))
                word = []
            out.append(ch)
        else:
            word.append(ch)
    if word:
        out.append("".join(word))
    return out


class WordPieceTokenizer:
    """Greedy longest-match WordPiece over a BERT ``vocab.txt``."""

    def __init__(self, vocab_path: str | Path, lowercase: bool = True):
        self.vocab: dict[str, int] = {}
        with open(vocab_path, encoding="utf-8") as f:
            for i, line in enumerate(f):
                self.vocab[line.rstrip("\n")] = i
        self.lowercase = lowercase
        self.pad_id = self.vocab.get("[PAD]", PAD_ID)
        self.unk_id = self.vocab.get("[UNK]", UNK_ID)
        self.cls_id = self.vocab.get("[CLS]", CLS_ID)
        self.sep_id = self.vocab.get("[SEP]", SEP_ID)

    @property
    def vocab_size(self) -> int:
        return len(self.vocab)

    def _word_ids(self, word: str, max_chars: int = 100) -> list[int]:
        if len(word) > max_chars:
            return [self.unk_id]
        ids: list[int] = []
        start = 0
        while start < len(word):
            end = len(word)
            cur = None
            while start < end:
                piece = word[start:end]
                if start > 0:
                    piece = "##" + piece
                if piece in self.vocab:
                    cur = self.vocab[piece]
                    break
                end -= 1
            if cur is None:
                return [self.unk_id]
            ids.append(cur)
            start = end
        return ids

    def encode(self, text: str, max_len: int) -> tuple[np.ndarray, np.ndarray]:
        """-> (ids, mask), each (max_len,) int64, [CLS] ... [SEP] + pad."""
        return _frame(self, text, max_len)


class HashingTokenizer:
    """Deterministic hashed-word ids — the no-vocab-file fallback.

    Ids land in ``[n_special, vocab_size)``; special ids keep the BERT layout
    so artifacts stay drop-in compatible with the model's embedding table.
    """

    def __init__(self, vocab_size: int = 30522, lowercase: bool = True):
        self.vocab_size = vocab_size
        self.lowercase = lowercase
        self.pad_id, self.cls_id, self.sep_id = PAD_ID, CLS_ID, SEP_ID
        self._floor = MASK_ID + 1

    def _word_ids(self, word: str) -> list[int]:
        h = int.from_bytes(hashlib.sha1(word.encode("utf-8")).digest()[:8], "little")
        return [self._floor + h % (self.vocab_size - self._floor)]

    def encode(self, text: str, max_len: int) -> tuple[np.ndarray, np.ndarray]:
        return _frame(self, text, max_len)


def _frame(tok, text: str, max_len: int) -> tuple[np.ndarray, np.ndarray]:
    """Shared [CLS] + word ids + truncate + [SEP] + pad/mask framing."""
    ids = [tok.cls_id]
    for w in basic_tokenize(text, tok.lowercase):
        ids.extend(tok._word_ids(w))
        if len(ids) >= max_len - 1:
            break
    ids = ids[: max_len - 1] + [tok.sep_id]
    mask = np.zeros(max_len, np.int64)
    mask[: len(ids)] = 1
    out = np.full(max_len, tok.pad_id, np.int64)
    out[: len(ids)] = ids
    return out, mask


def get_tokenizer(
    vocab_path: str | Path | None = None, vocab_size: int = 30522
) -> WordPieceTokenizer | HashingTokenizer:
    """WordPiece when a vocab file is given (must exist), hashing fallback
    only when no vocab was requested — a silently-wrong tokenizer would waste
    a whole preprocessing + training cycle."""
    if vocab_path is not None:
        if not Path(vocab_path).exists():
            raise FileNotFoundError(f"vocab file not found: {vocab_path}")
        return WordPieceTokenizer(vocab_path)
    return HashingTokenizer(vocab_size)
