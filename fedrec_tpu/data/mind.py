"""MIND dataset artifacts: loading the reference's preprocessed format.

The reference ships four artifacts under ``UserData/`` (reference
``main.py:148-157``):

  * ``bert_news_index.npy``  — int64 ``(N_news, 2, max_title_len)``:
    per-news stacked [token_ids; attention_mask]
  * ``bert_nid2index.pkl``   — dict ``nid str -> row index`` with ``<unk> -> 0``
  * ``train_sam_uid.pkl`` / ``valid_sam_uid.pkl`` — impression samples
    ``[uidx, pos_nid, neg_nids, history_nids, uid_str]``
    (field order per reference ``dataset.py:81``: ``_, pos, neg, his, _``)

This module loads those artifacts, plus a synthetic generator with identical
shapes/dtypes for tests and benchmarks (the repo ships only a 4-sample shard).
"""

from __future__ import annotations

import pickle
from dataclasses import dataclass
from pathlib import Path

import numpy as np


@dataclass
class MindData:
    news_tokens: np.ndarray          # (N_news, 2, title_len) int64
    nid2index: dict                  # nid -> row
    train_samples: list              # [uidx, pos, negs, history, uid]
    valid_samples: list

    @property
    def num_news(self) -> int:
        return self.news_tokens.shape[0]

    @property
    def title_len(self) -> int:
        return self.news_tokens.shape[2]


def load_mind_artifacts(data_dir: str | Path) -> MindData:
    data_dir = Path(data_dir)
    news_tokens = np.load(data_dir / "bert_news_index.npy", allow_pickle=True)
    with open(data_dir / "bert_nid2index.pkl", "rb") as f:
        nid2index = pickle.load(f)
    with open(data_dir / "train_sam_uid.pkl", "rb") as f:
        train_samples = pickle.load(f)
    with open(data_dir / "valid_sam_uid.pkl", "rb") as f:
        valid_samples = pickle.load(f)
    return MindData(news_tokens, nid2index, train_samples, valid_samples)


def _synth_news_table(rng, num_news: int, title_len: int, vocab: int):
    """Shared synthetic news-token table: variable-length titles with
    attention masks, row 0 = ``<unk>`` all-zero (reference artifact layout,
    ``nid2index['<unk>'] == 0``)."""
    news_tokens = np.zeros((num_news, 2, title_len), dtype=np.int64)
    lengths = rng.integers(min(5, title_len), title_len + 1, size=num_news)
    for i in range(1, num_news):
        ln = lengths[i]
        news_tokens[i, 0, :ln] = rng.integers(1000, vocab, size=ln)
        news_tokens[i, 1, :ln] = 1
    nids = [f"N{i}" for i in range(num_news)]
    nid2index = {"<unk>": 0}
    for i in range(1, num_news):
        nid2index[nids[i]] = i
    return news_tokens, nids, nid2index


def make_synthetic_mind(
    num_news: int = 512,
    num_train: int = 256,
    num_valid: int = 64,
    title_len: int = 50,
    vocab: int = 30522,
    his_len_range: tuple[int, int] = (5, 50),
    neg_pool_range: tuple[int, int] = (4, 40),
    seed: int = 0,
    popular_frac: float = 0.0,
) -> MindData:
    """Synthetic MIND-shaped data for tests/benchmarks.

    Index 0 is reserved for ``<unk>`` (all-zero tokens), matching the
    reference artifact layout where ``nid2index['<unk>'] == 0``.

    ``popular_frac > 0`` draws positives from only the first
    ``popular_frac * num_news`` items while negatives come from the rest —
    a popularity signal a recommender can actually learn, for
    loss-decreases tests.
    """
    rng = np.random.default_rng(seed)
    news_tokens, nids, nid2index = _synth_news_table(rng, num_news, title_len, vocab)

    n_popular = max(1, int(popular_frac * num_news)) if popular_frac > 0 else 0
    if n_popular and 1 + n_popular >= num_news:
        raise ValueError(
            f"popular_frac={popular_frac} leaves no negatives: "
            f"{n_popular} popular items of {num_news} news (need >= 2 non-popular)"
        )

    def _make(n_samples: int) -> list:
        samples = []
        for s in range(n_samples):
            his_len = int(rng.integers(*his_len_range, endpoint=True))
            pool_len = int(rng.integers(*neg_pool_range, endpoint=True))
            his = [nids[int(j)] for j in rng.integers(1, num_news, size=his_len)]
            if n_popular:
                pos = nids[int(rng.integers(1, 1 + n_popular))]
                negs = [
                    nids[int(j)]
                    for j in rng.integers(1 + n_popular, num_news, size=pool_len)
                ]
            else:
                negs = [nids[int(j)] for j in rng.integers(1, num_news, size=pool_len)]
                pos = nids[int(rng.integers(1, num_news))]
            samples.append([s, pos, negs, his, f"U{s}"])
        return samples

    return MindData(news_tokens, nid2index, _make(num_train), _make(num_valid))


def make_synthetic_mind_topics(
    num_news: int = 4096,
    num_train: int = 50_000,
    num_valid: int = 5_000,
    title_len: int = 50,
    bert_hidden: int = 768,
    num_topics: int = 20,
    topics_per_user: int = 2,
    p_pref_hist: float = 0.9,
    p_pref_pos: float = 0.9,
    signal_scale: float = 1.0,
    noise_scale: float = 1.0,
    his_len_range: tuple[int, int] = (5, 50),
    neg_pool_range: tuple[int, int] = (4, 40),
    seed: int = 0,
    dtype=np.float32,
) -> tuple[MindData, np.ndarray]:
    """Topic-structured synthetic corpus with a *recoverable* ranking signal.

    Unlike :func:`make_synthetic_mind` (popularity-only), this generator has
    the structure the two-tower model is actually built for: each news item
    carries a latent topic expressed in its frozen-trunk token states, each
    user prefers ``topics_per_user`` topics, their click history is drawn
    mostly (``p_pref_hist``) from preferred topics, and the clicked positive
    is preferred with probability ``p_pref_pos`` while pool negatives are
    uniform. A perfect topic-matcher therefore attains full-pool
    AUC ~= ``p_pref_pos * (1 - r) + 0.5 * (p_pref_pos * r + (1 - p_pref_pos)
    * (1 - r))`` with ``r = topics_per_user / num_topics`` (~0.90 at the
    defaults) — a known ceiling the learning curve can be judged against.

    Returns ``(MindData, token_states)`` where ``token_states`` is the
    ``(num_news, title_len, bert_hidden)`` cached-trunk tensor: per-news
    topic centroid + i.i.d. position noise (row 0 = ``<unk>`` = zeros).
    Serves VERDICT round-1 item 4 ("largest corpus obtainable offline with a
    recoverable signal") — the real-MIND path needs the raw tsv download
    (zero egress here); formats per reference ``main.py:148-157``.
    """
    if num_news - 1 < num_topics:
        raise ValueError(
            f"num_news={num_news} leaves fewer than num_topics={num_topics} "
            "real news items; every topic needs at least one"
        )
    rng = np.random.default_rng(seed)

    centroids = rng.standard_normal((num_topics, bert_hidden))
    centroids *= signal_scale / np.linalg.norm(centroids, axis=1, keepdims=True)
    # round-robin-then-shuffle: uniform-ish AND every topic non-empty (a
    # uniform draw leaves topics empty at small num_news, crashing the
    # preferred-topic sampler)
    topic_of = np.empty(num_news, dtype=np.int64)
    topic_of[1:] = rng.permutation(np.arange(num_news - 1) % num_topics)
    topic_of[0] = -1  # <unk>

    # draw directly in float32 (a float64 intermediate would transiently
    # double the ~600 MB the central accuracy corpus already needs)
    token_states = rng.standard_normal(
        (num_news, title_len, bert_hidden), dtype=np.float32
    )
    if np.dtype(dtype) != np.float32:
        token_states = token_states.astype(dtype)
    token_states *= noise_scale
    token_states[1:] += centroids[topic_of[1:], None, :].astype(dtype)
    token_states[0] = 0.0

    # news grouped by topic for O(1) preferred-topic draws
    by_topic = [np.flatnonzero(topic_of == t) for t in range(num_topics)]

    news_tokens, nids, nid2index = _synth_news_table(
        rng, num_news, title_len, vocab=30_522
    )

    topic_sizes = np.array([len(b) for b in by_topic])

    def _draw(pref_topics: np.ndarray, n: int, p_pref: float) -> np.ndarray:
        """n news ids: preferred-topic w.p. p_pref, else uniform non-unk."""
        out = rng.integers(1, num_news, size=n)
        pref = rng.random(n) < p_pref
        k = int(pref.sum())
        if k:
            ts = pref_topics[rng.integers(0, len(pref_topics), size=k)]
            within = rng.integers(0, topic_sizes[ts])
            out[pref] = [by_topic[t][i] for t, i in zip(ts, within)]
        return out

    def _make(n_samples: int, offset: int) -> list:
        samples = []
        for s in range(n_samples):
            pref = rng.choice(num_topics, size=topics_per_user, replace=False)
            his_len = int(rng.integers(*his_len_range, endpoint=True))
            pool_len = int(rng.integers(*neg_pool_range, endpoint=True))
            his = [nids[j] for j in _draw(pref, his_len, p_pref_hist)]
            pos = nids[int(_draw(pref, 1, p_pref_pos)[0])]
            negs = [nids[int(j)] for j in rng.integers(1, num_news, size=pool_len)]
            samples.append([offset + s, pos, negs, his, f"U{offset + s}"])
        return samples

    data = MindData(
        news_tokens, nid2index, _make(num_train, 0), _make(num_valid, num_train)
    )
    return data, token_states


def token_states_from_tokens(
    news_tokens: np.ndarray,
    bert_hidden: int = 96,
    vocab: int = 30_522,
    seed: int = 0,
    dtype=np.float32,
) -> np.ndarray:
    """(N, 2, L) token table -> (N, L, bert_hidden) "frozen random trunk".

    A deterministic surrogate for cached DistilBERT states when no
    pretrained trunk is available offline: every token id maps to a fixed
    Gaussian embedding, masked positions are zeroed. Lexical structure in
    the titles (shared topic words) therefore survives into the states, so
    ``text_encoder_mode='head'`` can learn from corpora produced by the
    real tokenizer/pipeline (the Adressa accuracy leg uses this). Not a
    language model — just the weakest trunk that preserves word identity.
    """
    ids = news_tokens[:, 0, :]
    # cover any tokenizer's id space (e.g. Norwegian BERT ~50k > the BERT
    # default); extending the table leaves ids < vocab with identical rows
    table = np.random.default_rng(seed).standard_normal(
        (max(vocab, int(ids.max()) + 1), bert_hidden), dtype=np.float32
    )
    mask = news_tokens[:, 1, :, None].astype(np.float32)
    states = table[ids] * mask
    return states.astype(dtype) if np.dtype(dtype) != np.float32 else states
