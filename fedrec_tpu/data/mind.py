"""MIND dataset artifacts: loading the reference's preprocessed format.

The reference ships four artifacts under ``UserData/`` (reference
``main.py:148-157``):

  * ``bert_news_index.npy``  — int64 ``(N_news, 2, max_title_len)``:
    per-news stacked [token_ids; attention_mask]
  * ``bert_nid2index.pkl``   — dict ``nid str -> row index`` with ``<unk> -> 0``
  * ``train_sam_uid.pkl`` / ``valid_sam_uid.pkl`` — impression samples
    ``[uidx, pos_nid, neg_nids, history_nids, uid_str]``
    (field order per reference ``dataset.py:81``: ``_, pos, neg, his, _``)

This module loads those artifacts, plus a synthetic generator with identical
shapes/dtypes for tests and benchmarks (the repo ships only a 4-sample shard).
"""

from __future__ import annotations

import pickle
from dataclasses import dataclass
from pathlib import Path

import numpy as np


@dataclass
class MindData:
    news_tokens: np.ndarray          # (N_news, 2, title_len) int64
    nid2index: dict                  # nid -> row
    train_samples: list              # [uidx, pos, negs, history, uid]
    valid_samples: list

    @property
    def num_news(self) -> int:
        return self.news_tokens.shape[0]

    @property
    def title_len(self) -> int:
        return self.news_tokens.shape[2]


def load_mind_artifacts(data_dir: str | Path) -> MindData:
    data_dir = Path(data_dir)
    news_tokens = np.load(data_dir / "bert_news_index.npy", allow_pickle=True)
    with open(data_dir / "bert_nid2index.pkl", "rb") as f:
        nid2index = pickle.load(f)
    with open(data_dir / "train_sam_uid.pkl", "rb") as f:
        train_samples = pickle.load(f)
    with open(data_dir / "valid_sam_uid.pkl", "rb") as f:
        valid_samples = pickle.load(f)
    return MindData(news_tokens, nid2index, train_samples, valid_samples)


def make_synthetic_mind(
    num_news: int = 512,
    num_train: int = 256,
    num_valid: int = 64,
    title_len: int = 50,
    vocab: int = 30522,
    his_len_range: tuple[int, int] = (5, 50),
    neg_pool_range: tuple[int, int] = (4, 40),
    seed: int = 0,
    popular_frac: float = 0.0,
) -> MindData:
    """Synthetic MIND-shaped data for tests/benchmarks.

    Index 0 is reserved for ``<unk>`` (all-zero tokens), matching the
    reference artifact layout where ``nid2index['<unk>'] == 0``.

    ``popular_frac > 0`` draws positives from only the first
    ``popular_frac * num_news`` items while negatives come from the rest —
    a popularity signal a recommender can actually learn, for
    loss-decreases tests.
    """
    rng = np.random.default_rng(seed)
    news_tokens = np.zeros((num_news, 2, title_len), dtype=np.int64)
    lengths = rng.integers(5, title_len + 1, size=num_news)
    for i in range(1, num_news):
        ln = lengths[i]
        news_tokens[i, 0, :ln] = rng.integers(1000, vocab, size=ln)
        news_tokens[i, 1, :ln] = 1
    nids = [f"N{i}" for i in range(num_news)]
    nid2index = {"<unk>": 0}
    for i in range(1, num_news):
        nid2index[nids[i]] = i

    n_popular = max(1, int(popular_frac * num_news)) if popular_frac > 0 else 0
    if n_popular and 1 + n_popular >= num_news:
        raise ValueError(
            f"popular_frac={popular_frac} leaves no negatives: "
            f"{n_popular} popular items of {num_news} news (need >= 2 non-popular)"
        )

    def _make(n_samples: int) -> list:
        samples = []
        for s in range(n_samples):
            his_len = int(rng.integers(*his_len_range, endpoint=True))
            pool_len = int(rng.integers(*neg_pool_range, endpoint=True))
            his = [nids[int(j)] for j in rng.integers(1, num_news, size=his_len)]
            if n_popular:
                pos = nids[int(rng.integers(1, 1 + n_popular))]
                negs = [
                    nids[int(j)]
                    for j in rng.integers(1 + n_popular, num_news, size=pool_len)
                ]
            else:
                negs = [nids[int(j)] for j in rng.integers(1, num_news, size=pool_len)]
                pos = nids[int(rng.integers(1, num_news))]
            samples.append([s, pos, negs, his, f"U{s}"])
        return samples

    return MindData(news_tokens, nid2index, _make(num_train), _make(num_valid))
