"""Negative sampling (behavioral parity with reference ``dataset.py:8-14``).

``newsample(pool, ratio)``: draw ``ratio`` negatives without replacement from
an impression's non-clicked pool; if the pool is smaller than ``ratio``, keep
the whole pool and pad with ``"<unk>"`` (index 0). The reference's global
``random`` module is replaced by an explicit ``numpy.random.Generator`` for
reproducibility across clients/hosts.
"""

from __future__ import annotations

import numpy as np

UNK = "<unk>"


def newsample(pool: list, ratio: int, rng: np.random.Generator | None = None) -> list:
    if ratio > len(pool):
        return list(pool) + [UNK] * (ratio - len(pool))
    if rng is None:
        rng = np.random.default_rng()
    idx = rng.choice(len(pool), size=ratio, replace=False)
    return [pool[i] for i in idx]


def sample_negatives_array(
    neg_pools: np.ndarray,
    neg_lens: np.ndarray,
    ratio: int,
    rng: np.random.Generator,
) -> np.ndarray:
    """Vectorized ``newsample`` over pre-indexed pools.

    ``neg_pools``: (N, max_pool) int32 of news indices, rows padded with 0.
    ``neg_lens``: (N,) actual pool sizes. Returns (N, ratio) int32 sampled
    negatives (without replacement where the pool allows; short pools keep all
    entries and pad with 0 = ``<unk>``, matching reference ``dataset.py:11-12``).
    """
    n, max_pool = neg_pools.shape
    if max_pool < ratio:
        # every pool is narrower than the request: widen with pad columns so
        # the take below always has `ratio` columns to select from
        neg_pools = np.pad(neg_pools, ((0, 0), (0, ratio - max_pool)))
        max_pool = ratio
    # random sort keys; padded slots pushed to +inf so they are never selected
    keys = rng.random((n, max_pool))
    keys = np.where(np.arange(max_pool)[None, :] < neg_lens[:, None], keys, np.inf)
    order = np.argsort(keys, axis=1)[:, :ratio]
    sampled = np.take_along_axis(neg_pools, order, axis=1)
    # rows with pool smaller than ratio: zero out the overflow slots
    valid = np.arange(ratio)[None, :] < np.minimum(neg_lens, ratio)[:, None]
    return np.where(valid, sampled, 0).astype(np.int32)
