"""Static-shape batch construction for the jitted train step.

Replaces the reference's torch ``TrainDataset`` + ``DataLoader`` +
``DistributedSampler`` stack (reference ``dataset.py:69-86``, ``main.py:166``)
with a vectorized numpy pipeline that emits fixed-shape device-ready arrays:

  * candidates: (B, 1 + npratio) int32 news indices, positive at slot 0 and
    label fixed to 0 (reference ``dataset.py:83,85-86``)
  * history:    (B, max_his_len) int32, most-recent-last, padded with 0
    (= ``<unk>``; reference pads with 0 at ``dataset.py:84``)
  * his_len:    (B,) int32 true history lengths (the reference does not mask
    history padding — the model treats masking as an option, default off for
    parity)

All shapes are static so XLA compiles the step exactly once. Per-epoch
negative re-sampling matches the reference's ``newsample`` call inside
``__getitem__`` (fresh negatives every epoch).

Both this batcher and the native C++ one (``native_batcher``) compose with
the bounded host prefetcher (``fedrec_tpu.data.prefetch``,
``data.prefetch_batches``): the Trainer iterates epochs through it so batch
t+1 assembles on a producer thread while step t runs on device.

Divergence (ledger): histories longer than ``max_his_len`` are truncated to
the most recent ``max_his_len`` clicks. The reference's pad expression
``his + [0]*(max_his_len - len(his))`` silently produces ragged rows for long
histories (reference ``dataset.py:84``), which cannot batch; the shipped
demo shard indeed contains a 140-click history.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Iterator

import numpy as np

from fedrec_tpu.data.sampling import sample_negatives_array


@dataclass
class Batch:
    candidates: np.ndarray    # (..., 1 + npratio) int32
    history: np.ndarray       # (..., max_his_len) int32
    his_len: np.ndarray       # (...,) int32
    labels: np.ndarray        # (...,) int32, always 0 (positive at slot 0)


@dataclass
class IndexedSamples:
    """Samples pre-indexed once into dense arrays (host-side)."""

    pos: np.ndarray           # (N,) int32
    neg_pools: np.ndarray     # (N, max_pool) int32, padded with 0
    neg_lens: np.ndarray      # (N,) int32
    history: np.ndarray       # (N, max_his_len) int32
    his_len: np.ndarray       # (N,) int32
    # user index per sample (the reference record's uidx field) — carried
    # for user-level telemetry (activity slices in obs.quality); None for
    # pre-existing callers that build the arrays directly
    uidx: np.ndarray | None = None

    def __len__(self) -> int:
        return self.pos.shape[0]

    def take(self, idx: np.ndarray) -> "IndexedSamples":
        """Row subset (e.g. one process's shard of the sample set)."""
        return IndexedSamples(
            pos=self.pos[idx],
            neg_pools=self.neg_pools[idx],
            neg_lens=self.neg_lens[idx],
            history=self.history[idx],
            his_len=self.his_len[idx],
            uidx=None if self.uidx is None else self.uidx[idx],
        )


def index_samples(samples: list, nid2index: dict, max_his_len: int) -> IndexedSamples:
    """One-time conversion of ``[uidx, pos, negs, his, uid]`` records to arrays."""
    n = len(samples)
    max_pool = max((len(s[2]) for s in samples), default=1)
    max_pool = max(max_pool, 1)
    pos = np.zeros(n, dtype=np.int32)
    neg_pools = np.zeros((n, max_pool), dtype=np.int32)
    neg_lens = np.zeros(n, dtype=np.int32)
    history = np.zeros((n, max_his_len), dtype=np.int32)
    his_len = np.zeros(n, dtype=np.int32)
    uidx = np.zeros(n, dtype=np.int64)
    for i, (u, p, negs, his, _) in enumerate(samples):
        uidx[i] = int(u)
        pos[i] = nid2index[p]
        neg_idx = [nid2index[x] for x in negs]
        neg_pools[i, : len(neg_idx)] = neg_idx
        neg_lens[i] = len(neg_idx)
        his_idx = [nid2index[x] for x in his][-max_his_len:]  # keep most recent
        history[i, : len(his_idx)] = his_idx
        his_len[i] = len(his_idx)
    return IndexedSamples(pos, neg_pools, neg_lens, history, his_len, uidx=uidx)


def shard_indices(
    n: int, num_shards: int, shard_id: int, rng: np.random.Generator | None = None
) -> np.ndarray:
    """Equal-size round-robin shard of ``range(n)``.

    ``DistributedSampler`` parity (reference ``main.py:166``): indices are
    (optionally) shuffled, padded by wrap-around to a multiple of
    ``num_shards``, then dealt round-robin so every shard sees the same count.
    """
    idx = np.arange(n)
    if rng is not None:
        idx = rng.permutation(idx)
    total = -(-n // num_shards) * num_shards  # ceil to multiple
    if total > n:
        # tiled wrap-around pad: fills even when num_shards > 2n
        idx = np.concatenate([idx, np.resize(idx, total - n)])
    return idx[shard_id::num_shards]


def process_shard_indices(n: int, num_shards: int, shard_index: int, seed: int = 0) -> np.ndarray:
    """Disjoint cross-PROCESS shard of ``range(n)`` for the coordinator
    deployment — each host trains its own slice of the corpus, the premise
    of federation. The reference shards by global rank via
    ``DistributedSampler`` (reference ``main.py:166``, ``client.py:243-249``).

    Divergence (ledger): ``DistributedSampler`` wrap-pads every rank to an
    equal count, duplicating up to ``world-1`` samples globally. Here shards
    are truly disjoint (sizes differ by at most 1) so that
    ``fed.weight_by_samples`` weighs honest per-host counts. The permutation
    is seeded, so every process deals the identical deck and the shards
    partition the sample set exactly.
    """
    if not 0 <= shard_index < num_shards:
        raise ValueError(f"shard_index {shard_index} not in [0, {num_shards})")
    perm = np.random.default_rng((seed, 0xD15C)).permutation(n)
    return np.sort(perm[shard_index::num_shards])


class TrainBatcher:
    """Yields static-shape batches; optionally stacked across clients.

    ``epoch_batches``: (B, ...) batches for one client / single-program mode.
    ``epoch_batches_sharded``: (num_clients, B, ...) stacked batches where
    leading axis aligns with the mesh's ``clients`` axis — the SPMD analogue
    of per-rank ``DistributedSampler`` shards.
    """

    def __init__(
        self,
        indexed: IndexedSamples,
        batch_size: int,
        npratio: int = 4,
        shuffle: bool = True,
        drop_remainder: bool = True,
        seed: int = 0,
    ):
        self.indexed = indexed
        self.batch_size = batch_size
        self.npratio = npratio
        self.shuffle = shuffle
        self.drop_remainder = drop_remainder
        self.seed = seed

    # ------------------------------------------------------------------
    def _epoch_order(self, epoch: int, n: int) -> np.ndarray:
        if self.shuffle:
            return np.random.default_rng((self.seed, epoch, 0xB)).permutation(n)
        return np.arange(n)

    def _assemble(self, take: np.ndarray, rng: np.random.Generator) -> Batch:
        ix = self.indexed
        negs = sample_negatives_array(
            ix.neg_pools[take], ix.neg_lens[take], self.npratio, rng
        )
        candidates = np.concatenate([ix.pos[take][:, None], negs], axis=1)
        return Batch(
            candidates=candidates.astype(np.int32),
            history=ix.history[take],
            his_len=ix.his_len[take],
            labels=np.zeros(take.shape[0], dtype=np.int32),
        )

    def num_batches(self, n: int | None = None) -> int:
        n = len(self.indexed) if n is None else n
        if self.drop_remainder:
            return n // self.batch_size
        return -(-n // self.batch_size)

    # ------------------------------------------------------------------
    def epoch_batches(self, epoch: int = 0) -> Iterator[Batch]:
        n = len(self.indexed)
        order = self._epoch_order(epoch, n)
        # one sampling stream per epoch: every batch draws fresh keys, but the
        # whole epoch is reproducible from (seed, epoch)
        rng = np.random.default_rng((self.seed, epoch, 0xA))
        for b in range(self.num_batches(n)):
            take = order[b * self.batch_size : (b + 1) * self.batch_size]
            if len(take) < self.batch_size:
                # wrap-around pad (tiled, so it fills even when B > 2n)
                pad = np.resize(order, self.batch_size - len(take))
                take = np.concatenate([take, pad])
            yield self._assemble(take, rng)

    def epoch_batches_sharded(self, num_clients: int, epoch: int = 0) -> Iterator[Batch]:
        """Stacked per-client batches: arrays shaped (num_clients, B, ...)."""
        n = len(self.indexed)
        order = self._epoch_order(epoch, n)
        # order is already shuffled; shards deal round-robin over it
        shards = [order[shard_indices(n, num_clients, c)] for c in range(num_clients)]
        per_client = min(len(s) for s in shards)
        rng = np.random.default_rng((self.seed, epoch, 0xA))
        for b in range(self.num_batches(per_client)):
            client_batches = []
            for c in range(num_clients):
                take = shards[c][b * self.batch_size : (b + 1) * self.batch_size]
                if len(take) < self.batch_size:
                    pad = np.resize(shards[c], self.batch_size - len(take))
                    take = np.concatenate([take, pad])
                client_batches.append(self._assemble(take, rng))
            yield Batch(
                candidates=np.stack([cb.candidates for cb in client_batches]),
                history=np.stack([cb.history for cb in client_batches]),
                his_len=np.stack([cb.his_len for cb in client_batches]),
                labels=np.stack([cb.labels for cb in client_batches]),
            )

    def epoch_arrays_sharded(self, num_clients: int, epoch: int = 0) -> Batch:
        """Whole epoch stacked as (steps, num_clients, B, ...) for ``lax.scan``."""
        batches = list(self.epoch_batches_sharded(num_clients, epoch))
        if not batches:
            raise ValueError("no batches: dataset smaller than num_clients*batch_size")
        return Batch(
            candidates=np.stack([b.candidates for b in batches]),
            history=np.stack([b.history for b in batches]),
            his_len=np.stack([b.his_len for b in batches]),
            labels=np.stack([b.labels for b in batches]),
        )
