from fedrec_tpu.data.mind import MindData, load_mind_artifacts, make_synthetic_mind
from fedrec_tpu.data.sampling import newsample
from fedrec_tpu.data.batcher import (
    Batch,
    IndexedSamples,
    TrainBatcher,
    index_samples,
    shard_indices,
)

__all__ = [
    "Batch",
    "IndexedSamples",
    "MindData",
    "TrainBatcher",
    "index_samples",
    "load_mind_artifacts",
    "make_synthetic_mind",
    "newsample",
    "shard_indices",
]
