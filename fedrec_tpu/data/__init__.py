from fedrec_tpu.data.mind import (
    MindData,
    load_mind_artifacts,
    make_synthetic_mind,
    make_synthetic_mind_topics,
    token_states_from_tokens,
)
from fedrec_tpu.data.sampling import newsample
from fedrec_tpu.data.batcher import (
    Batch,
    IndexedSamples,
    TrainBatcher,
    index_samples,
    process_shard_indices,
    shard_indices,
)
from fedrec_tpu.data.adressa import (
    make_synthetic_adressa_events,
    parse_adressa_events,
    preprocess_adressa,
)
from fedrec_tpu.data.native_batcher import (
    NativeTrainBatcher,
    is_available as native_batcher_available,
)
from fedrec_tpu.data.prefetch import Prefetcher, maybe_prefetch
from fedrec_tpu.data.preprocess import (
    build_news_index,
    parse_behaviors_tsv,
    parse_news_tsv,
    preprocess_mind,
    write_artifacts,
)
from fedrec_tpu.data.tokenizer import (
    HashingTokenizer,
    WordPieceTokenizer,
    get_tokenizer,
)

__all__ = [
    "Batch",
    "HashingTokenizer",
    "IndexedSamples",
    "MindData",
    "NativeTrainBatcher",
    "Prefetcher",
    "TrainBatcher",
    "maybe_prefetch",
    "native_batcher_available",
    "WordPieceTokenizer",
    "build_news_index",
    "get_tokenizer",
    "index_samples",
    "load_mind_artifacts",
    "make_synthetic_mind",
    "make_synthetic_adressa_events",
    "make_synthetic_mind_topics",
    "newsample",
    "parse_adressa_events",
    "parse_behaviors_tsv",
    "preprocess_adressa",
    "parse_news_tsv",
    "preprocess_mind",
    "process_shard_indices",
    "shard_indices",
    "token_states_from_tokens",
    "write_artifacts",
]
