# Convenience targets. The native C++ data engine has its own Makefile
# (native/Makefile); this one is for repo-level workflows.

.PHONY: t1 native obs-smoke chaos-smoke comm-cost

# tier-1 verify: the ROADMAP.md pipeline, DOTS_PASSED count included
t1:
	@bash scripts/t1.sh

# observability smoke: 2-round CPU training + serve_load, then assert the
# artifact trio (metrics.jsonl / trace.json / prometheus.txt) renders
obs-smoke:
	@bash scripts/obs_smoke.sh

# robustness smoke: seeded FaultPlan (dropout + nan + scale-poison) under
# trimmed-mean aggregation — completes, reproduces bit-identically, and the
# recovery leg quarantines + rolls back instead of aborting
chaos-smoke:
	@bash scripts/chaos_smoke.sh

# communication-cost benchmark: measured per-codec wire buffers of the
# flagship trees + the bytes-per-round x time-to-AUC tradeoff runs (CPU);
# banks benchmarks/comm_cost.json
comm-cost:
	@python benchmarks/comm_cost.py

native:
	$(MAKE) -C native
