# Convenience targets. The native C++ data engine has its own Makefile
# (native/Makefile); this one is for repo-level workflows.

.PHONY: t1 lint check native obs-smoke chaos-smoke shard-smoke elastic-smoke comm-cost pallas-bench table-capacity quality-gate quality-smoke perf-gate agg-scale async-smoke watch-smoke churn-soak

# tier-1 verify: the ROADMAP.md pipeline, DOTS_PASSED count included
t1:
	@bash scripts/t1.sh

# static analysis: fedrec-lint (project invariants, docs/ANALYSIS.md) +
# the generic layer (ruff when installed; builtin GL rules always)
lint:
	@bash scripts/lint.sh

# the one local PR gate: lint, then tier-1
check:
	@bash scripts/check.sh

# observability smoke: 2-round CPU training + serve_load, then assert the
# artifact trio (metrics.jsonl / trace.json / prometheus.txt) renders
obs-smoke:
	@bash scripts/obs_smoke.sh

# robustness smoke: seeded FaultPlan (dropout + nan + scale-poison) under
# trimmed-mean aggregation — completes, reproduces bit-identically, and the
# recovery leg quarantines + rolls back instead of aborting
chaos-smoke:
	@bash scripts/chaos_smoke.sh

# sharding smoke: a REAL 2-process gloo CPU world (2x4 fake devices, one
# global 8-device mesh) running the sharded-catalog train step — asserts
# survival, rows/device = padded/8, bit-identity with the replicated
# table, and fsdp at-rest sharding with cross-process-identical losses
shard-smoke:
	@bash scripts/shard_smoke.sh

# elastic-federation smoke: a 4-process gloo world under epoch-based
# membership loses one peer to a chaos kill, shrinks-and-continues at
# world 3, reintegrates the supervisor-respawned peer at world 4,
# finishes every round + the final eval, and the membership counters
# match the script (exactly one shrink, one rejoin, worlds 4 -> 3 -> 4)
elastic-smoke:
	@bash scripts/elastic_smoke.sh

# catalog-capacity benchmark: rows-per-device x devices frontier
# (replicated vs sharded) + a measured sharded-gather exactness/latency
# leg on the local backend; banks benchmarks/table_capacity.json
table-capacity:
	@python benchmarks/table_capacity.py

# quality-regression gate: seeded CPU run -> sliced-eval digest; banks a
# provenance-stamped benchmarks/quality_gate.json on first run, then
# fails (naming the slice) when any slice's AUC regresses beyond the
# noise-aware threshold vs the banked baseline
quality-gate:
	@python benchmarks/quality_gate.py

# model-quality smoke: sliced-eval telemetry end to end (2-round CPU run
# with obs.quality on -> Quality report section + slice gauges), a store
# drift-probe leg (corrupted table push -> non-zero serve.drift_* BEFORE
# the swap), and a forced-regression gate-failure leg
quality-smoke:
	@bash scripts/quality_smoke.sh

# perf-regression gate: seeded CPU measurement of the flagship step +
# host pipeline (steps/s, batch-build/h2d ms, dispatch gaps, analytic
# FLOPs); banks a provenance-stamped benchmarks/perf_gate.json on first
# run, then fails (naming the lane) on any noise-adjusted regression vs
# the banked baseline — the perf analog of quality-gate
perf-gate:
	@python benchmarks/perf_gate.py

# aggregation-scale frontier: round time vs cohort size (1k/10k/100k
# logical clients) for flat vs hierarchical vs async aggregation on the
# real fedrec_tpu.agg kernels; proves hierarchical round time sub-linear
# in cohort size at 10k+ and the async quorum cut beating the flat
# barrier; banks benchmarks/agg_scale.json on first run, then checks
agg-scale:
	@python benchmarks/agg_scale.py

# buffered-async smoke: an agg.server commit authority + 4 async workers
# (one chaos-delayed 4s) — asserts the global commits at quorum 3 while
# the straggler is still sleeping, the late contribution folds into the
# NEXT commit (late_folds >= 1), and the delayed worker's marginal
# commit gate is ~0 in the fleet report (the barrier would have charged
# it the full straggle)
async-smoke:
	@bash scripts/async_smoke.sh

# partition-tolerance soak: 104 wire workers against a live commit
# authority + membership service through a seeded churn schedule (10%
# kills, half rejoining, a full partition window on one cohort's edge,
# in-flight push duplication on another, an authority kill/respawn from
# its state sidecars mid-run) — asserts monotone commit liveness, zero
# acked-push loss via ledger reconciliation, bounded folded staleness,
# duplicate detection without re-folding, incarnation-2 recovery, and
# the fleet watch layer naming the partitioned edge; banks
# benchmarks/churn_soak.json
churn-soak:
	@python benchmarks/churn_soak.py

# continuous-watch smoke: a forced SLO breach (tight round-time objective
# the JIT compile round blows through) must fire AND resolve through the
# alert lifecycle, an unmeetable SLO must hold `fedrec-obs alerts`/`tail
# --once` at exit 1, and the obs.slo-disabled path must leave zero watch
# footprint (no alert records, no alert.* instruments)
watch-smoke:
	@bash scripts/watch_smoke.sh

# communication-cost benchmark: measured per-codec wire buffers of the
# flagship trees + the bytes-per-round x time-to-AUC tradeoff runs (CPU);
# banks benchmarks/comm_cost.json
comm-cost:
	@python benchmarks/comm_cost.py

# attention/fused-kernel microbenchmark: XLA dense vs pallas vs chunked at
# H in {50,1024,2048,4096} plus the fused hot-path legs (B in {256,1024} +
# the gather+encode leg); refuses to run off-TPU (interpret mode measures
# nothing) — benchmarks/chip_watcher.sh queues it for the next live window
pallas-bench:
	@python benchmarks/pallas_bench.py

native:
	$(MAKE) -C native
