# Convenience targets. The native C++ data engine has its own Makefile
# (native/Makefile); this one is for repo-level workflows.

.PHONY: t1 native

# tier-1 verify: the ROADMAP.md pipeline, DOTS_PASSED count included
t1:
	@bash scripts/t1.sh

native:
	$(MAKE) -C native
