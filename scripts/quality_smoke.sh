#!/bin/bash
# Model-quality observability smoke (ISSUE-14 acceptance scenarios), CPU:
#
#   1. a seeded 2-round synthetic training run with obs.quality.enabled:
#      asserts the sliced-eval gauges land in prometheus.txt
#      (eval_auc{slice=...}, eval_ece), the run report renders a Quality
#      section, and `fedrec-obs quality` renders the per-slice table;
#   2. a serve probe leg: an EmbeddingStore with the drift probe armed
#      publishes a healthy swap (zero drift) and a corrupted-table push —
#      the corrupted push must surface non-zero serve.drift_* metrics
#      BEFORE the swap, and the admin metrics dict must carry them;
#   3. a forced-regression gate leg: a fresh baseline is banked into a
#      scratch dir, a clean check passes (exit 0), and a seeded
#      perturbation of one category bucket must FAIL the gate (exit 1)
#      naming the slice.
#
#   scripts/quality_smoke.sh     # or: make quality-smoke
#
# Artifacts land under /tmp/fedrec_quality_smoke for inspection.
set -euo pipefail
cd "$(dirname "$0")/.."

OUT=${QUALITY_SMOKE_DIR:-/tmp/fedrec_quality_smoke}
rm -rf "$OUT"
mkdir -p "$OUT"

run() {
    env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
        XLA_FLAGS="--xla_force_host_platform_device_count=8" "$@"
}

echo "== [1/3] 2-round CPU training run with obs.quality =="
run python -m fedrec_tpu.cli.run 2 16 2 --strategy param_avg --clients 8 \
    --synthetic --synthetic-train 512 --synthetic-news 128 \
    --mode joint \
    --obs-dir "$OUT/train" \
    --set obs.quality.enabled=1 --set obs.quality.hist_len_edges=4,7 \
    --set model.news_dim=32 --set model.num_heads=4 --set model.head_dim=8 \
    --set model.query_dim=16 --set model.bert_hidden=48 \
    --set data.max_his_len=10 --set data.max_title_len=12 \
    --set train.snapshot_dir="$OUT/train_snap" --set train.eval_every=1 \
    --set train.eval_protocol=full > "$OUT/train.log" 2>&1 \
    || { tail -30 "$OUT/train.log"; exit 1; }

grep -q 'eval_auc{slice="all"}' "$OUT/train/prometheus.txt" \
    || { echo "prometheus.txt missing eval_auc{slice=all}"; exit 1; }
grep -q 'eval_auc{slice="category=b0"}' "$OUT/train/prometheus.txt" \
    || { echo "prometheus.txt missing category slice gauges"; exit 1; }
grep -q 'eval_ece' "$OUT/train/prometheus.txt" \
    || { echo "prometheus.txt missing eval_ece"; exit 1; }
python -m fedrec_tpu.cli.obs report "$OUT/train" > "$OUT/report.txt"
grep -q '^## Quality' "$OUT/report.txt" \
    || { echo "run report missing Quality section"; exit 1; }
python -m fedrec_tpu.cli.obs quality "$OUT/train" > "$OUT/quality.txt" \
    || { echo "fedrec-obs quality failed"; cat "$OUT/quality.txt"; exit 1; }
grep -q 'category=b' "$OUT/quality.txt" \
    || { echo "quality report missing slice table"; exit 1; }
SLICES=$(python -m fedrec_tpu.cli.obs quality "$OUT/train" --json \
    | python -c 'import json,sys; print(len(json.load(sys.stdin)["slices"]))')
[ "$SLICES" -ge 8 ] || { echo "want >= 8 slices, got $SLICES"; exit 1; }
echo "  train: Quality section + $SLICES slice gauges + ece rendered"

echo "== [2/3] serve drift-probe leg =="
run python - "$OUT" <<'EOF'
import sys

import numpy as np

from fedrec_tpu.obs import dump_artifacts, get_registry
from fedrec_tpu.serving.store import EmbeddingStore

out = sys.argv[1]
store = EmbeddingStore()
store.enable_drift_probe(num_probes=32, topk=10, seed=0)
rng = np.random.default_rng(0)
vecs = rng.standard_normal((2000, 32)).astype(np.float32)

store.publish(vecs, {"w": 1}, source="initial")
store.publish(vecs.copy(), {"w": 1}, source="healthy-refresh")
m = store.metrics()
assert m["drift_score_shift_mean"] == 0.0, m
assert m["drift_topk_jaccard"] == 1.0 and m["drift_rank_churn"] == 0.0, m
print("  healthy swap: zero drift, jaccard 1.0")

# a corrupted table push: the probe must flag it BEFORE it serves
corrupt = vecs + 3.0 * rng.standard_normal(vecs.shape).astype(np.float32)
store.publish(corrupt, {"w": 1}, source="corrupted")
m = store.metrics()
assert m["drift_score_shift_mean"] > 0, m
assert m["drift_rank_churn"] > 0.2, m
reg = get_registry()
assert reg.get("serve.drift_checks_total").value() == 2
dump_artifacts(f"{out}/serve")
print(f"  corrupted push: |Δscore| mean={m['drift_score_shift_mean']:.3f}, "
      f"rank churn={m['drift_rank_churn']:.3f} (surfaced pre-swap)")
EOF
grep -q 'serve_drift_rank_churn' "$OUT/serve/prometheus.txt" \
    || { echo "serve prometheus.txt missing drift gauges"; exit 1; }

echo "== [3/3] quality-regression gate: bank, pass, forced failure =="
run python benchmarks/quality_gate.py --bank --out "$OUT/quality_gate.json" \
    > "$OUT/gate_bank.log" 2>&1 \
    || { tail -10 "$OUT/gate_bank.log"; exit 1; }
grep -q 'QUALITY_GATE=BANKED' "$OUT/gate_bank.log"
run python benchmarks/quality_gate.py --check --out "$OUT/quality_gate.json" \
    > "$OUT/gate_pass.log" 2>&1 \
    || { echo "clean gate check failed"; tail -10 "$OUT/gate_pass.log"; exit 1; }
grep -q 'QUALITY_GATE=PASS' "$OUT/gate_pass.log"
if run python benchmarks/quality_gate.py --check --perturb-bucket 0 \
    --out "$OUT/quality_gate.json" > "$OUT/gate_fail.log" 2>&1; then
    echo "perturbed gate check exited 0 — the regression went undetected"
    tail -10 "$OUT/gate_fail.log"
    exit 1
fi
grep -q 'QUALITY_GATE=FAIL' "$OUT/gate_fail.log"
grep -q 'REGRESSION slice category=b0' "$OUT/gate_fail.log" \
    || { echo "gate failure did not name the perturbed slice"; \
         tail -10 "$OUT/gate_fail.log"; exit 1; }
echo "  gate: banked + clean pass + forced regression caught (category=b0)"
echo "QUALITY_SMOKE=PASS"
