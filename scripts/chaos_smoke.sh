#!/bin/bash
# Chaos smoke (ISSUE-5 acceptance scenarios), CPU-only:
#
#   1. FAULT-FREE BASELINE: a 3-round trimmed-mean run; final loss banked.
#   2. CHAOS RUN: the same config under a seeded FaultPlan — 30% dropout +
#      one nan-update client + one x100 scale-poison client — with
#      coordinate-wise trimmed mean (trim_k=2: two byzantine clients).
#      Must complete all rounds with FINITE losses, and `fedrec-obs
#      report` must render a Robustness section with the injected-fault
#      counts.
#   3. DETERMINISM: re-run the same plan; the per-round training_loss
#      trajectory must be BIT-IDENTICAL.
#   4. RECOVERY: an injected nan-update with fed.robust.recover=true —
#      quarantine + rollback + a completed run (no flight-recorder
#      abort), rollback visible in the registry counters.
#   5. POPULATION (ISSUE-6): 1024 logical clients sampled 64/round onto
#      the 8x8 slot mesh under 20% seeded dropout + lognormal straggle +
#      a 200ms round deadline and a 16-report quorum — must survive all
#      rounds with finite losses, over-selection visible (80 sampled),
#      dropouts/deadline-cuts counted, quorum held, and the whole run
#      (losses AND churn counters) bit-identical on re-run.
#   6. COMPRESSED (ISSUE-7): the population scenario with the sign1bit
#      update codec (error feedback on) + trimmed-mean aggregation —
#      robust x compress via decode-before-reduce. Must survive with
#      finite losses, bank measured uplink bytes (Communication section
#      in the report, ratio > 20x), and replay bit-identically from the
#      chaos seed.
#
#   scripts/chaos_smoke.sh     # or: make chaos-smoke
#
# Artifacts land under /tmp/fedrec_chaos_smoke for inspection.
set -euo pipefail
cd "$(dirname "$0")/.."

OUT=${CHAOS_SMOKE_DIR:-/tmp/fedrec_chaos_smoke}
rm -rf "$OUT"
mkdir -p "$OUT"

run() {
    env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
        XLA_FLAGS="--xla_force_host_platform_device_count=8" "$@"
}

SMALL=(
    --set model.news_dim=32 --set model.num_heads=4 --set model.head_dim=8
    --set model.query_dim=16 --set model.bert_hidden=48
    --set data.max_his_len=10 --set data.max_title_len=12
    --set train.eval_every=1000 --set train.eval_protocol=sampled
    --set fed.robust.method=trimmed_mean
)
CHAOS=(
    --set chaos.enabled=true --set chaos.seed=7 --set chaos.drop_rate=0.3
    --set "chaos.faults=nan@*:3,scale@*:5x100"
    --set fed.robust.trim_k=2
    --set obs.health.abort_on_nonfinite=false
)

echo "== [1/6] fault-free trimmed-mean baseline =="
run python -m fedrec_tpu.cli.run 3 8 10 --strategy param_avg --clients 8 \
    --mode joint --synthetic --synthetic-train 256 --synthetic-news 64 \
    --obs-dir "$OUT/baseline" "${SMALL[@]}" \
    --set train.snapshot_dir="$OUT/base_snap" \
    > "$OUT/baseline.log" 2>&1 || { tail -30 "$OUT/baseline.log"; exit 1; }

echo "== [2/6] chaos run: 30% dropout + nan client + x100 poison client =="
run python -m fedrec_tpu.cli.run 3 8 10 --strategy param_avg --clients 8 \
    --mode joint --synthetic --synthetic-train 256 --synthetic-news 64 \
    --obs-dir "$OUT/chaos_a" "${SMALL[@]}" "${CHAOS[@]}" \
    --set train.snapshot_dir="$OUT/chaos_a_snap" \
    > "$OUT/chaos_a.log" 2>&1 || { tail -30 "$OUT/chaos_a.log"; exit 1; }

echo "== [3/6] determinism: same plan, bit-identical trajectory =="
run python -m fedrec_tpu.cli.run 3 8 10 --strategy param_avg --clients 8 \
    --mode joint --synthetic --synthetic-train 256 --synthetic-news 64 \
    --obs-dir "$OUT/chaos_b" "${SMALL[@]}" "${CHAOS[@]}" \
    --set train.snapshot_dir="$OUT/chaos_b_snap" \
    > "$OUT/chaos_b.log" 2>&1 || { tail -30 "$OUT/chaos_b.log"; exit 1; }

echo "== [4/6] recovery: nan client + fed.robust.recover=true =="
run python -m fedrec_tpu.cli.run 4 8 10 --strategy param_avg --clients 8 \
    --mode joint --synthetic --synthetic-train 256 --synthetic-news 64 \
    --obs-dir "$OUT/recover" "${SMALL[@]}" \
    --set chaos.enabled=true --set "chaos.faults=nan@1:3" \
    --set fed.robust.recover=true \
    --set train.snapshot_dir="$OUT/recover_snap" \
    > "$OUT/recover.log" 2>&1 || { tail -30 "$OUT/recover.log"; exit 1; }

POP=(
    --set fed.population.num_clients=1024
    --set fed.population.over_select=1.25
    --set fed.population.round_deadline_ms=200
    --set fed.population.min_reports=16
    --set fed.population.seed=11
    --set chaos.enabled=true --set chaos.seed=13
    --set chaos.pop_drop_rate=0.2 --set chaos.pop_straggle_ms=50
)

echo "== [5/6] population: 1024 logical clients, 64/round, 20% dropout =="
run python -m fedrec_tpu.cli.run 3 2 10 --strategy param_avg --clients 64 \
    --mode joint --synthetic --synthetic-train 2048 --synthetic-news 64 \
    --obs-dir "$OUT/pop_a" "${SMALL[@]}" "${POP[@]}" \
    --set train.snapshot_dir="$OUT/pop_a_snap" \
    > "$OUT/pop_a.log" 2>&1 || { tail -30 "$OUT/pop_a.log"; exit 1; }
run python -m fedrec_tpu.cli.run 3 2 10 --strategy param_avg --clients 64 \
    --mode joint --synthetic --synthetic-train 2048 --synthetic-news 64 \
    --obs-dir "$OUT/pop_b" "${SMALL[@]}" "${POP[@]}" \
    --set train.snapshot_dir="$OUT/pop_b_snap" \
    > "$OUT/pop_b.log" 2>&1 || { tail -30 "$OUT/pop_b.log"; exit 1; }

COMPRESS=(
    --set fed.dcn_compress=sign1bit
    --set fed.robust.trim_k=1
)

echo "== [6/6] compressed: sign1bit + trimmed_mean + population dropout =="
run python -m fedrec_tpu.cli.run 3 2 10 --strategy param_avg --clients 64 \
    --mode joint --synthetic --synthetic-train 2048 --synthetic-news 64 \
    --obs-dir "$OUT/comp_a" "${SMALL[@]}" "${POP[@]}" "${COMPRESS[@]}" \
    --set train.snapshot_dir="$OUT/comp_a_snap" \
    > "$OUT/comp_a.log" 2>&1 || { tail -30 "$OUT/comp_a.log"; exit 1; }
run python -m fedrec_tpu.cli.run 3 2 10 --strategy param_avg --clients 64 \
    --mode joint --synthetic --synthetic-train 2048 --synthetic-news 64 \
    --obs-dir "$OUT/comp_b" "${SMALL[@]}" "${POP[@]}" "${COMPRESS[@]}" \
    --set train.snapshot_dir="$OUT/comp_b_snap" \
    > "$OUT/comp_b.log" 2>&1 || { tail -30 "$OUT/comp_b.log"; exit 1; }

run python - "$OUT" <<'EOF'
import json, math, sys
from pathlib import Path

out = Path(sys.argv[1])

def losses(d):
    rows = {}
    for line in (out / d / "metrics.jsonl").read_text().splitlines():
        try:
            r = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(r, dict) and "training_loss" in r and "round" in r:
            rows[int(r["round"])] = r["training_loss"]
    return [rows[k] for k in sorted(rows)]

base, a, b = losses("baseline"), losses("chaos_a"), losses("chaos_b")
assert len(a) == 3 and all(map(math.isfinite, a)), f"chaos run not finite: {a}"
assert a == b, f"chaos trajectory not bit-identical:\n{a}\n{b}"
assert all(map(math.isfinite, base))
# robust run's loss within shouting distance of the fault-free baseline
assert abs(a[-1] - base[-1]) < 0.25, (a[-1], base[-1])

from fedrec_tpu.obs.report import build_report, load_jsonl
records, snaps = load_jsonl(out / "chaos_a" / "metrics.jsonl")
rb = build_report(records, snaps).get("robustness")
assert rb and rb.get("robust_method") == "trimmed_mean", rb
fi = rb.get("faults_injected", {})
assert fi.get("nan", 0) >= 3 and fi.get("scale", 0) >= 3 and fi.get("drop", 0) >= 1, fi

rec_records, rec_snaps = load_jsonl(out / "recover" / "metrics.jsonl")
rrb = build_report(rec_records, rec_snaps)["robustness"]
assert rrb.get("rollbacks", 0) >= 1 and rrb.get("quarantines", 0) >= 1, rrb
rec = losses("recover")
assert len(rec) == 4 and all(map(math.isfinite, rec)), rec
import math as _math
pa, pb = losses("pop_a"), losses("pop_b")
assert len(pa) == 3 and all(map(_math.isfinite, pa)), f"population run not finite: {pa}"
assert pa == pb, f"population trajectory not bit-identical:\n{pa}\n{pb}"

def pop_part(d):
    records, snaps = load_jsonl(out / d / "metrics.jsonl")
    return build_report(records, snaps).get("participation")

part_a, part_b = pop_part("pop_a"), pop_part("pop_b")
assert part_a and part_a["population"] == 1024, part_a
assert part_a["cohort_sampled"] == 80, part_a           # ceil(64 * 1.25)
assert part_a["cohort_reporting"] >= 16, part_a         # quorum held
assert part_a.get("dropouts", 0) > 0, part_a            # churn visible
assert part_a == part_b, f"population churn not bit-identical:\n{part_a}\n{part_b}"

# leg 6: sign1bit + trimmed_mean + population dropout (robust x compress)
ca, cb = losses("comp_a"), losses("comp_b")
assert len(ca) == 3 and all(map(_math.isfinite, ca)), f"compressed run not finite: {ca}"
assert ca == cb, f"compressed trajectory not bit-identical:\n{ca}\n{cb}"

def comm_section(d):
    records, snaps = load_jsonl(out / d / "metrics.jsonl")
    return build_report(records, snaps).get("communication")

comm = comm_section("comp_a")
assert comm and comm["bytes_up"].get("cohort", 0) > 0, comm   # measured uplink
assert comm["compression_ratio"] > 20, comm                   # ~32x sign1bit
assert comm == comm_section("comp_b"), "compressed byte accounting not bit-identical"
crb = None
records_c, snaps_c = load_jsonl(out / "comp_a" / "metrics.jsonl")
crb = build_report(records_c, snaps_c).get("robustness")
assert crb and crb.get("robust_method") == "trimmed_mean", crb  # decode-before-reduce ran

print("chaos smoke OK")
print(f"  baseline   losses: {base}")
print(f"  chaos      losses: {a}  (bit-identical on re-run)")
print(f"  recovery   losses: {rec}  rollbacks={rrb['rollbacks']:.0f} quarantines={rrb['quarantines']:.0f}")
print(f"  population losses: {pa}  (bit-identical on re-run)")
print(f"  compressed losses: {ca}  (sign1bit+trimmed_mean, bit-identical on re-run; "
      f"uplink {comm['bytes_up']['cohort']/2**20:.2f} MB at {comm['compression_ratio']:.0f}x)")
print(f"  population churn : sampled={part_a['cohort_sampled']:.0f} reporting={part_a['cohort_reporting']:.0f} "
      f"dropouts={part_a.get('dropouts', 0):.0f} deadline_cuts={part_a.get('deadline_cuts', 0):.0f} "
      f"coverage={part_a.get('coverage', 0):.1%}")
EOF

echo "chaos smoke PASSED; artifacts in $OUT"
