#!/bin/bash
# Tier-1 verify in one line — the EXACT pipeline from ROADMAP.md, so builder
# and reviewer stop pasting it by hand. Prints the DOTS_PASSED count (dots in
# pytest's progress lines — the roadmap's cross-session pass metric) and
# exits with pytest's status.
#
#   scripts/t1.sh          # or: make t1
#
# Log lands in /tmp/_t1.log for post-mortems.
set -o pipefail
cd "$(dirname "$0")/.."
rm -f /tmp/_t1.log
timeout -k 10 1500 env JAX_PLATFORMS=cpu \
  python -m pytest tests/ -q -m 'not slow' --continue-on-collection-errors \
  -p no:cacheprovider -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log
rc=${PIPESTATUS[0]}
echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c)
exit $rc
