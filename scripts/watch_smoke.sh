#!/bin/bash
# Continuous-watch smoke (ISSUE-19 acceptance scenario), CPU-only:
#
#   1. FIRE -> RESOLVE: a 3-round synthetic run with an SLO the round-0
#      JIT compile breaches (train.round_seconds:p95<2.5 — compile costs
#      seconds, steady-state rounds are sub-second) and 1-evaluation
#      windows/confirmation. The alert must FIRE naming the SLO, the
#      metric and the worker, then RESOLVE once compiled rounds pass;
#      `fedrec-obs alerts` renders both transitions and exits 0, the run
#      report carries the Alerts panel, the prometheus exposition the
#      alert.* instruments.
#   2. STAYS FIRING: the same run against an unmeetable SLO (<1e-9) —
#      the alert never resolves; `fedrec-obs alerts` and
#      `fedrec-obs tail --once` must exit 1 (the CI-able contract).
#   3. DISABLED PATH: obs.slo left at its default (false) — no
#      {"kind":"alert"} record, no alert_* instrument in the exposition.
#
#   scripts/watch_smoke.sh     # or: make watch-smoke
#
# Artifacts land under /tmp/fedrec_watch_smoke for inspection.
set -euo pipefail
cd "$(dirname "$0")/.."

OUT=${WATCH_SMOKE_DIR:-/tmp/fedrec_watch_smoke}
rm -rf "$OUT"
mkdir -p "$OUT"

run() {
    env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
        XLA_FLAGS="--xla_force_host_platform_device_count=8" "$@"
}

TINY=(--set model.news_dim=32 --set model.num_heads=4 --set model.head_dim=8
      --set model.query_dim=16 --set model.bert_hidden=48
      --set data.max_his_len=10 --set data.max_title_len=12)

echo "== [1/3] forced breach: fire on the compile round, resolve after =="
run python -m fedrec_tpu.cli.run 3 16 3 --strategy param_avg --clients 8 \
    --synthetic --synthetic-train 256 --synthetic-news 128 --mode joint \
    --obs-dir "$OUT/obs" "${TINY[@]}" \
    --set train.snapshot_dir="$OUT/snap" \
    --set obs.slo.enabled=true \
    --set "obs.slo.objectives=round_time:train.round_seconds:p95<2.5" \
    --set obs.slo.fast_window=1 --set obs.slo.slow_window=2 \
    --set obs.watch.pending_for=1 --set obs.watch.resolve_after=1 \
    > "$OUT/train.log" 2>&1 || { tail -30 "$OUT/train.log"; exit 1; }

python - "$OUT" <<'EOF'
import json, sys
out = sys.argv[1]
recs = [json.loads(l) for l in open(f"{out}/obs/metrics.jsonl")]
alerts = [r for r in recs if r.get("kind") == "alert"
          and r.get("key") == "slo:round_time"]
events = [r["event"] for r in alerts]
assert "firing" in events and "resolved" in events, (
    f"want a full fire->resolve lifecycle, got {events}")
fire = next(r for r in alerts if r["event"] == "firing")
# the alert names the SLO, the metric, and the offending worker
assert fire["labels"]["slo"] == "round_time", fire
assert fire["labels"]["metric"] == "train.round_seconds", fire
assert fire["labels"].get("worker") is not None, fire
assert "SLO round_time burning" in fire["summary"], fire
assert fire["value"] > 2.5, fire              # the compile-round p95
print(f"  lifecycle ok: {events}; fired at p95={fire['value']:.2f}s "
      f"on worker {fire['labels']['worker']}")
EOF

# the exit contract, quiet side: everything resolved -> 0
run python -m fedrec_tpu.cli.obs alerts "$OUT/obs" > "$OUT/alerts.txt"
grep -q "FIRING" "$OUT/alerts.txt" && grep -q "RESOLVED" "$OUT/alerts.txt" \
    || { echo "alerts timeline missing transitions"; cat "$OUT/alerts.txt"; exit 1; }

# surfaces: the Alerts panel in the run report, alert.* in the exposition
python -m fedrec_tpu.cli.obs report "$OUT/obs" > "$OUT/report.txt"
grep -q "^## Alerts" "$OUT/report.txt" \
    || { echo "no Alerts panel in the run report"; exit 1; }
grep -q "alert_transitions_total" "$OUT/obs/prometheus.txt" \
    || { echo "no alert.* instruments in the exposition"; exit 1; }
echo "  surfaces ok: alerts verb exit 0, report panel, prometheus rows"

echo "== [2/3] unmeetable SLO: stays firing, alerts/tail exit 1 =="
run python -m fedrec_tpu.cli.run 2 16 3 --strategy param_avg --clients 8 \
    --synthetic --synthetic-train 256 --synthetic-news 128 --mode joint \
    --obs-dir "$OUT/obs_hot" "${TINY[@]}" \
    --set train.snapshot_dir="$OUT/snap_hot" \
    --set obs.slo.enabled=true \
    --set "obs.slo.objectives=round_time:train.round_seconds:p95<1e-9" \
    --set obs.slo.fast_window=1 --set obs.slo.slow_window=2 \
    --set obs.watch.pending_for=1 --set obs.watch.resolve_after=1 \
    > "$OUT/train_hot.log" 2>&1 || { tail -30 "$OUT/train_hot.log"; exit 1; }

set +e
run python -m fedrec_tpu.cli.obs alerts "$OUT/obs_hot" > "$OUT/alerts_hot.txt"
RC_ALERTS=$?
run python -m fedrec_tpu.cli.obs tail "$OUT/obs_hot" --once > /dev/null
RC_TAIL=$?
set -e
[ "$RC_ALERTS" -eq 1 ] \
    || { echo "alerts exit $RC_ALERTS while firing (want 1)"; exit 1; }
[ "$RC_TAIL" -eq 1 ] \
    || { echo "tail --once exit $RC_TAIL while firing (want 1)"; exit 1; }
grep -q "slo:round_time" "$OUT/alerts_hot.txt" \
    || { echo "active table missing the firing SLO"; exit 1; }
echo "  exit contract ok: alerts=1, tail --once=1 while firing"

echo "== [3/3] disabled path: no alert records, no alert.* instruments =="
run python -m fedrec_tpu.cli.run 1 16 3 --strategy param_avg --clients 8 \
    --synthetic --synthetic-train 256 --synthetic-news 128 --mode joint \
    --obs-dir "$OUT/obs_off" "${TINY[@]}" \
    --set train.snapshot_dir="$OUT/snap_off" \
    > "$OUT/train_off.log" 2>&1 || { tail -30 "$OUT/train_off.log"; exit 1; }
if grep -q '"kind": "alert"' "$OUT/obs_off/metrics.jsonl"; then
    echo "disabled run emitted alert records"; exit 1
fi
if grep -q "alert_" "$OUT/obs_off/prometheus.txt"; then
    echo "disabled run registered alert.* instruments"; exit 1
fi
echo "  disabled path ok: zero watch footprint"
echo "WATCH_SMOKE=PASS"
