#!/bin/bash
# Sharding smoke (ISSUE-11 acceptance scenarios), CPU-only:
#
#   1. 2-PROCESS GLOO EXCHANGE + FULL STEP: a REAL two-process world
#      (jax.distributed + gloo CPU collectives, the coordinator
#      deployment's rendezvous), 2 x 4 fake devices = one global
#      8-device mesh, with the token-state table row-sharded across
#      BOTH processes' devices — rows/device == padded/8 asserted from
#      the addressable shards — the owner-bucketed all_to_all gather
#      crossing the process boundary over real gloo TCP (rows
#      BIT-IDENTICAL to `full_table[ids]`), and the FULL federated
#      train step through the sharded catalog, with both processes'
#      results asserted bit-equal. (The full-step leg was previously
#      blocked on a gloo transport flake — a TCP pair dying at the
#      first collective, the same pair.cc error that failed
#      tests/test_multihost_world.py at HEAD; the bounded
#      rendezvous-retry + transport probe in initialize_distributed
#      now turns that flake into a retried bring-up.)
#   2. SHARDED-TABLE STEP EQUALITY: the federated train step through
#      the sharded catalog on the 8-device mesh must be BIT-IDENTICAL
#      to the replicated-table step (the degenerate-config equality),
#      per-batch AND rounds-in-jit.
#   3. FSDP STEP EQUALITY: a (clients=4, fsdp=2) mesh with the at-rest
#      state sharded per the size-aware policy — step + round-end sync
#      bit-identical to the 1-D replicated baseline, and the at-rest
#      buffers actually sharded (per-device bytes < replicated).
#
#   scripts/shard_smoke.sh     # or: make shard-smoke
#
# Artifacts land under /tmp/fedrec_shard_smoke for inspection.
set -euo pipefail
cd "$(dirname "$0")/.."

OUT=${SHARD_SMOKE_DIR:-/tmp/fedrec_shard_smoke}
rm -rf "$OUT"
mkdir -p "$OUT"

free_port() {
    python - <<'PY'
import socket
s = socket.socket(); s.bind(("127.0.0.1", 0)); print(s.getsockname()[1]); s.close()
PY
}

# ---------------------------------------------- leg 1: 2-process gloo world
cat > "$OUT/gloo_worker.py" <<'PYEOF'
import os, sys

os.environ.pop("PALLAS_AXON_POOL_IPS", None)
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + " --xla_force_host_platform_device_count=4"
).strip()

from functools import partial

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from fedrec_tpu.compat import shard_map
from fedrec_tpu.parallel.multihost import initialize_distributed
from fedrec_tpu.shard.table import ShardedNewsTable, owner_bucketed_gather

port, pid = sys.argv[1], int(sys.argv[2])
initialize_distributed(f"127.0.0.1:{port}", 2, pid)
assert jax.device_count() == 8, "global world must see 2x4 devices"

mesh = Mesh(np.array(jax.devices()).reshape(8), ("clients",))
rng = np.random.default_rng(0)
N, L, D = 1000, 12, 48  # not divisible by 8: padding path
full = rng.standard_normal((N, L, D)).astype(np.float32)
tab = ShardedNewsTable.create(full, mesh, "clients")
assert tab.spec.rows_per_shard == tab.spec.padded_rows // 8
local_rows = {s.data.shape[0] for s in tab.rows.addressable_shards}
assert local_rows == {tab.spec.rows_per_shard}, local_rows

U = 64
ids = rng.integers(0, N, (8, U)).astype(np.int32)


@partial(
    shard_map, mesh=mesh,
    in_specs=(P("clients"), P("clients")), out_specs=P("clients"),
    check_vma=False,
)
def gather(rows, ids_blk):
    return owner_bucketed_gather(rows, ids_blk[0], tab.spec)[None]


out = jax.jit(gather)(
    tab.rows, jax.device_put(ids, NamedSharding(mesh, P("clients")))
)
rep = jax.jit(lambda t: t, out_shardings=NamedSharding(mesh, P()))(out)
np.testing.assert_array_equal(np.asarray(rep), full[ids])
print(
    f"GLOO_GATHER_OK {pid} rows/dev={tab.spec.rows_per_shard} "
    f"ids/client={U}",
    flush=True,
)

# ---- full-step leg: the federated train step THROUGH the sharded
# catalog across the 2-process world (identical deterministic setup on
# both processes; each process_put slices out its addressable shards)
from pathlib import Path

from fedrec_tpu.config import ExperimentConfig
from fedrec_tpu.fed import get_strategy
from fedrec_tpu.models import NewsRecommender
from fedrec_tpu.train import build_fed_train_step
from fedrec_tpu.train.state import init_client_state, replicate_state

outdir = Path(sys.argv[3])
cfg = ExperimentConfig()
cfg.model.news_dim = 32
cfg.model.num_heads = 4
cfg.model.head_dim = 8
cfg.model.query_dim = 16
cfg.model.bert_hidden = D
cfg.model.text_encoder_mode = "head"
cfg.model.dropout_rate = 0.0
cfg.data.max_his_len = 10
cfg.data.max_title_len = L
cfg.data.batch_size = 8
cfg.fed.num_clients = 8
cfg.shard.table = True

model = NewsRecommender(cfg.model)
st = replicate_state(
    init_client_state(model, cfg, jax.random.PRNGKey(0), N, L),
    8, jax.random.PRNGKey(1),
)


def to_global(x, spec=P("clients")):
    # make_array_from_callback builds each process's addressable shards
    # LOCALLY from the (identical, same-seed) host value — zero
    # collectives. device_put against a multi-host sharding would issue
    # a cross-process value-check broadcast PER LEAF, and concurrent
    # small broadcasts are exactly where this rig's gloo transport
    # desyncs (pair.cc preamble mismatches).
    x = np.asarray(x)
    return jax.make_array_from_callback(
        x.shape, NamedSharding(mesh, spec), lambda idx: x[idx]
    )


st = jax.tree_util.tree_map(to_global, st)
rng2 = np.random.default_rng(7)
b = cfg.data.batch_size
batch = {
    "candidates": rng2.integers(
        0, N, (8, b, 1 + cfg.data.npratio)
    ).astype(np.int32),
    "history": rng2.integers(
        0, N, (8, b, cfg.data.max_his_len)
    ).astype(np.int32),
    "labels": np.zeros((8, b), np.int32),
}
batch = {k: to_global(v) for k, v in batch.items()}
step = build_fed_train_step(
    model, cfg, get_strategy("param_avg"), mesh, mode="joint",
    sharded_table=tab.spec,
)
out_state, metrics = step(st, batch, tab.rows)
rep_step = jax.jit(lambda t: t, out_shardings=NamedSharding(mesh, P()))(
    (out_state.user_params, out_state.news_params, metrics["loss"])
)
flat_u = np.concatenate(
    [np.ravel(np.asarray(x)) for x in jax.tree_util.tree_leaves(rep_step[0])]
)
flat_n = np.concatenate(
    [np.ravel(np.asarray(x)) for x in jax.tree_util.tree_leaves(rep_step[1])]
)
loss = np.asarray(rep_step[2])
assert np.isfinite(loss).all(), loss
np.savez(outdir / f"step_{pid}.npz", user=flat_u, news=flat_n, loss=loss)
print(f"GLOO_STEP_OK {pid} loss_mean={float(loss.mean()):.5f}", flush=True)
PYEOF

run_worker() {
    env -u PALLAS_AXON_POOL_IPS -u XLA_FLAGS JAX_PLATFORMS=cpu \
        PYTHONPATH="$PWD${PYTHONPATH:+:$PYTHONPATH}" \
        python "$OUT/gloo_worker.py" "$2" "$1" "$OUT" \
        > "$OUT/gloo_worker_$1.log" 2>&1
}

# Bounded whole-world retry: the rig's gloo transport can drop a TCP
# pair MID-RUN (pair.cc read/framing errors), which no in-process retry
# can recover — the coordination runtime is poisoned. Bring-up flakes
# are already retried inside initialize_distributed (transport probe +
# port schedule); a mid-run pair death relaunches BOTH workers on a
# fresh port. Only the gloo transport signature retries — any other
# failure is a real regression and fails immediately.
LEG_OK=0
for ATTEMPT in 1 2 3; do
    PORT=$(free_port)
    rm -f "$OUT"/step_*.npz
    run_worker 0 "$PORT" & P0=$!
    run_worker 1 "$PORT" & P1=$!
    FAIL=0
    wait "$P0" || FAIL=1
    wait "$P1" || FAIL=1
    if [ "$FAIL" -eq 0 ]; then
        LEG_OK=1
        break
    fi
    if [ "$ATTEMPT" -lt 3 ] \
        && grep -qE "pair\.cc|[Gg]loo" "$OUT"/gloo_worker_*.log; then
        echo "[shard-smoke] gloo transport flake (attempt $ATTEMPT);" \
             "relaunching the 2-process world on a fresh port"
        continue
    fi
    break
done
if [ "$LEG_OK" -ne 1 ]; then
    echo "[shard-smoke] 2-process gloo leg FAILED — worker logs:"
    cat "$OUT"/gloo_worker_*.log
    exit 1
fi
grep -h "GLOO_GATHER_OK" "$OUT"/gloo_worker_*.log
grep -h "GLOO_STEP_OK" "$OUT"/gloo_worker_*.log

# the 2-process step leg's results are bit-equal across processes
python - <<PYEOF
import numpy as np
a = np.load("$OUT/step_0.npz")
b = np.load("$OUT/step_1.npz")
np.testing.assert_array_equal(a["user"], b["user"])
np.testing.assert_array_equal(a["news"], b["news"])
np.testing.assert_array_equal(a["loss"], b["loss"])
print("[shard-smoke] 2-process full-step bit-equality OK")
PYEOF

# ------------------------------- legs 2+3: step equality on the 8-dev mesh
env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
    XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    PYTHONPATH="$PWD${PYTHONPATH:+:$PYTHONPATH}" \
    python - <<'PYEOF'
import numpy as np
import jax
import jax.numpy as jnp

from fedrec_tpu.config import ExperimentConfig
from fedrec_tpu.fed import get_strategy
from fedrec_tpu.models import NewsRecommender
from fedrec_tpu.parallel import client_mesh, fed_mesh, shard_batch
from fedrec_tpu.shard import (
    ShardedNewsTable, fsdp_state_shardings,
)
from fedrec_tpu.train import (
    build_fed_round_scan, build_fed_train_step, build_param_sync,
    shard_round_batches, stack_rounds,
)
from fedrec_tpu.train.state import init_client_state, replicate_state


def tiny_cfg(**over):
    cfg = ExperimentConfig()
    cfg.model.news_dim = 32
    cfg.model.num_heads = 4
    cfg.model.head_dim = 8
    cfg.model.query_dim = 16
    cfg.model.bert_hidden = 48
    cfg.model.text_encoder_mode = "head"
    cfg.data.max_his_len = 10
    cfg.data.max_title_len = 12
    cfg.data.batch_size = 8
    for k, v in over.items():
        section, key = k.split("__")
        setattr(getattr(cfg, section), key, v)
    return cfg


def setup(cfg, num_news=100, seed=0):
    rng = np.random.default_rng(seed)
    ts = rng.standard_normal(
        (num_news, cfg.data.max_title_len, cfg.model.bert_hidden)
    ).astype(np.float32)
    model = NewsRecommender(cfg.model)
    st = replicate_state(
        init_client_state(
            model, cfg, jax.random.PRNGKey(0), num_news,
            cfg.data.max_title_len,
        ),
        cfg.fed.num_clients, jax.random.PRNGKey(1),
    )
    b = cfg.data.batch_size
    batch = {
        "candidates": rng.integers(
            0, num_news, (cfg.fed.num_clients, b, 1 + cfg.data.npratio)
        ).astype(np.int32),
        "history": rng.integers(
            0, num_news, (cfg.fed.num_clients, b, cfg.data.max_his_len)
        ).astype(np.int32),
        "labels": np.zeros((cfg.fed.num_clients, b), np.int32),
    }
    return model, ts, st, batch


def leaves(tree):
    return [np.asarray(x) for x in jax.tree_util.tree_leaves(tree)]


# ---- leg 2: sharded catalog == dense, per-batch AND rounds-in-jit
cfg = tiny_cfg(fed__num_clients=8)
model, ts, st0, batch = setup(cfg)
mesh = client_mesh(8)
tab = ShardedNewsTable.create(ts, mesh, "clients")

step_d = build_fed_train_step(
    model, cfg, get_strategy("param_avg"), mesh, mode="joint"
)
step_s = build_fed_train_step(
    model, cfg, get_strategy("param_avg"), mesh, mode="joint",
    sharded_table=tab.spec,
)
_, _, st0b, _ = setup(cfg)
sd, md = step_d(st0, shard_batch(mesh, batch), jnp.asarray(ts))
ss, ms = step_s(st0b, shard_batch(mesh, batch), tab.rows)
np.testing.assert_array_equal(np.asarray(md["loss"]), np.asarray(ms["loss"]))
for a, b in zip(leaves(sd.user_params), leaves(ss.user_params)):
    np.testing.assert_array_equal(a, b)
print("STEP_EQUALITY_OK per-batch")

rs_d = build_fed_round_scan(
    model, cfg, get_strategy("param_avg"), mesh, mode="joint"
)
rs_s = build_fed_round_scan(
    model, cfg, get_strategy("param_avg"), mesh, mode="joint",
    sharded_table=tab.spec,
)
stacked = shard_round_batches(mesh, stack_rounds([[batch], [batch]]), cfg)
w = jnp.ones((2, 8), jnp.float32)
_, _, r0a, _ = setup(cfg)
_, _, r0b, _ = setup(cfg)
ra, ma = rs_d(r0a, stacked, jnp.asarray(ts), w)
rb, mb = rs_s(r0b, stacked, tab.rows, w)
np.testing.assert_array_equal(np.asarray(ma["loss"]), np.asarray(mb["loss"]))
for a, b in zip(leaves(ra.user_params), leaves(rb.user_params)):
    np.testing.assert_array_equal(a, b)
print("STEP_EQUALITY_OK rounds-in-jit")

# ---- leg 3: fsdp at-rest sharding == 1-D replicated baseline
cfg_f = tiny_cfg(fed__num_clients=4)
cfg_f.shard.fsdp = 2
cfg_f.shard.fsdp_min_size_mb = 0.0
mesh_f = fed_mesh(cfg_f)
model_f, ts_f, st_f0, batch_f = setup(cfg_f, seed=3)
shardings = fsdp_state_shardings(st_f0, mesh_f, cfg_f)
placed = jax.tree_util.tree_map(
    lambda x, s: jax.device_put(jnp.asarray(x), s), st_f0, shardings
)
rep_bytes = sum(x.nbytes for x in leaves(st_f0))
local_bytes = sum(
    max(s.data.nbytes for s in x.addressable_shards)
    for x in jax.tree_util.tree_leaves(placed)
)
assert local_bytes < rep_bytes, (local_bytes, rep_bytes)
step_f = build_fed_train_step(
    model_f, cfg_f, get_strategy("param_avg"), mesh_f, mode="joint",
    state_shardings=shardings,
)
sync_f = build_param_sync(
    cfg_f, mesh_f, get_strategy("param_avg"), state_shardings=shardings
)
sf, mf = step_f(placed, shard_batch(mesh_f, batch_f), jnp.asarray(ts_f))
sf = sync_f(sf, jnp.ones((4,), jnp.float32))

cfg_b = tiny_cfg(fed__num_clients=4)
mesh_b = client_mesh(4, max_devices=4)
model_b, ts_b, st_b0, _ = setup(cfg_b, seed=3)
step_b = build_fed_train_step(
    model_b, cfg_b, get_strategy("param_avg"), mesh_b, mode="joint"
)
sync_b = build_param_sync(cfg_b, mesh_b, get_strategy("param_avg"))
sb, mb2 = step_b(st_b0, shard_batch(mesh_b, batch_f), jnp.asarray(ts_b))
sb = sync_b(sb, jnp.ones((4,), jnp.float32))
np.testing.assert_array_equal(np.asarray(mf["loss"]), np.asarray(mb2["loss"]))
for a, b in zip(leaves(sf.user_params), leaves(sb.user_params)):
    np.testing.assert_array_equal(a, b)
print(f"FSDP_EQUALITY_OK bytes/dev={local_bytes} replicated={rep_bytes}")
PYEOF

echo "[shard-smoke] OK"
