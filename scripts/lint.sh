#!/bin/bash
# Static-analysis gate — fedrec-lint (the project-invariant analyzers,
# docs/ANALYSIS.md) plus the generic layer.
#
#   scripts/lint.sh          # or: make lint
#
# The generic layer runs twice-over where possible: fedrec-lint's builtin
# GL9xx rules always run (stdlib-only, every rig has them), and when ruff
# is installed the [tool.ruff] subset from pyproject.toml runs too (a
# superset-checker of the same pure-bug rules). Exit nonzero on any
# finding from either.
set -o pipefail
cd "$(dirname "$0")/.."

rc=0

echo "[lint] fedrec-lint (TS/CC/MC/FM/DA/GL, docs/ANALYSIS.md)"
python -m fedrec_tpu.cli.lint --stats || rc=1

if command -v ruff >/dev/null 2>&1; then
    echo "[lint] ruff ([tool.ruff] subset from pyproject.toml)"
    ruff check fedrec_tpu benchmarks bench.py || rc=1
elif python -c "import ruff" >/dev/null 2>&1; then
    echo "[lint] ruff ([tool.ruff] subset from pyproject.toml)"
    python -m ruff check fedrec_tpu benchmarks bench.py || rc=1
else
    echo "[lint] ruff not installed — builtin GL9xx rules covered the generic layer"
fi

exit $rc
