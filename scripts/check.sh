#!/bin/bash
# The one local PR gate: static analysis, then tier-1.
#
#   scripts/check.sh         # or: make check
#
# Lint runs first because it is ~2 s against tier-1's ~14 min — a doc-drift
# or dead-flag finding should not cost a full test run to discover.
set -e
cd "$(dirname "$0")/.."

bash scripts/lint.sh
bash scripts/t1.sh
