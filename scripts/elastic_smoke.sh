#!/bin/bash
# Elastic-federation smoke (ISSUE-12 acceptance), CPU-only:
#
#   A 4-process gloo world under elastic membership
#   (fedrec_tpu.parallel.membership) loses one peer to a chaos kill
#   mid-run and must
#
#     1. SHRINK-AND-CONTINUE: the survivors re-form as membership epoch 1
#        at world 3 and keep federating (NOT 4 standalone forks — the
#        pre-elastic failure mode);
#     2. REJOIN: the killed peer's supervisor respawns it (held off by
#        chaos.rejoin_delay_s so the shrink is observable first); its
#        join knocks on the healthy epoch, the server broadcasts the
#        reformation at a round boundary, and epoch 2 re-forms at
#        world 4;
#     3. FINISH: the full-complement world completes every round and the
#        final evaluation runs;
#     4. ACCOUNT: the membership service's counters match the script —
#        exactly one shrink, exactly one rejoin, epoch history
#        world 4 -> 3 -> 4.
#
#   scripts/elastic_smoke.sh     # or: make elastic-smoke
#
# Artifacts land under /tmp/fedrec_elastic_smoke for inspection.
set -euo pipefail
cd "$(dirname "$0")/.."

OUT=${ELASTIC_SMOKE_DIR:-/tmp/fedrec_elastic_smoke}
rm -rf "$OUT"
mkdir -p "$OUT"

MPORT=$(python - <<'PY'
import socket
s = socket.socket(); s.bind(("127.0.0.1", 0)); print(s.getsockname()[1]); s.close()
PY
)

ROUNDS=10

# ------------------------------------------------ the membership service
env -u PALLAS_AXON_POOL_IPS \
    PYTHONPATH="$PWD${PYTHONPATH:+:$PYTHONPATH}" \
    python -m fedrec_tpu.parallel.membership "127.0.0.1:$MPORT" \
    --target-world 4 \
    > "$OUT/membership.log" 2>&1 &
MEM_PID=$!
cleanup() { kill "$MEM_PID" 2>/dev/null || true; }
trap cleanup EXIT
sleep 1

# --------------------------------------------------- 4 supervised workers
run_worker() {
    env -u PALLAS_AXON_POOL_IPS -u XLA_FLAGS JAX_PLATFORMS=cpu \
        FEDREC_SUPERVISE_MAX=12 \
        PYTHONPATH="$PWD${PYTHONPATH:+:$PYTHONPATH}" \
        python -m fedrec_tpu.cli.coordinator "$ROUNDS" 8 1 \
        --supervise \
        --membership "127.0.0.1:$MPORT" \
        --num-processes 4 --process-id "$1" \
        --synthetic --synthetic-train 960 --synthetic-news 64 \
        --clients 1 --server-trains \
        --collective-timeout 15 \
        --set model.bert_hidden=48 --set data.max_his_len=10 \
        --set data.max_title_len=12 --set model.news_dim=32 \
        --set model.num_heads=4 --set model.head_dim=8 \
        --set model.query_dim=16 \
        --set "train.snapshot_dir=$OUT/d$1" \
        --set "train.eval_every=$ROUNDS" \
        --set fed.weight_by_samples=true \
        --set optim.user_lr=0.001 --set optim.news_lr=0.001 \
        --set chaos.enabled=true \
        --set chaos.kill_round=2 --set chaos.kill_process=2 \
        --set chaos.rejoin_delay_s=15 \
        --set fed.elastic.lease_ms=5000 \
        --set fed.elastic.heartbeat_ms=1000 \
        --set fed.elastic.formation_grace_ms=6000 \
        > "$OUT/worker_$1.log" 2>&1
}

PIDS=()
for pid in 0 1 2 3; do
    run_worker "$pid" & PIDS+=($!)
done
FAIL=0
for i in 0 1 2 3; do
    wait "${PIDS[$i]}" || { echo "[elastic-smoke] worker $i FAILED"; FAIL=1; }
done
if [ "$FAIL" -ne 0 ]; then
    echo "[elastic-smoke] worker logs:"
    tail -n 40 "$OUT"/worker_*.log
    exit 1
fi

# --------------------------------------------------------- the assertions
env -u PALLAS_AXON_POOL_IPS \
    PYTHONPATH="$PWD${PYTHONPATH:+:$PYTHONPATH}" \
    OUT="$OUT" MPORT="$MPORT" ROUNDS="$ROUNDS" \
    python - <<'PY'
import json
import os
from pathlib import Path

from fedrec_tpu.parallel.membership import MembershipClient

out = Path(os.environ["OUT"])
rounds = int(os.environ["ROUNDS"])
st = MembershipClient(
    f"127.0.0.1:{os.environ['MPORT']}", worker_id="_smoke"
).status()
print("[elastic-smoke] membership status:", json.dumps(st))
hist = [h["world"] for h in st["epoch_history"]]

# 1. the initial epoch formed at the full complement
assert hist and hist[0] == 4, hist
# 2. shrink-and-continue: exactly one shrink, to world 3
assert st["shrinks"] == 1, st
assert 3 in hist, hist
# 3. rejoin: exactly one, and the world grew back to 4
assert st["rejoins"] == 1, st
assert hist[-1] == 4, hist
assert hist == [4, 3, 4], hist
# the dead peer's lease expired exactly once
assert st["lease_misses"] >= 1, st

w2 = (out / "worker_2.log").read_text()
assert "dying at round 2" in w2, "the chaos kill never fired"
assert w2.count("dying at round 2") == 1, "marker guard failed"
assert "holding off its rejoin" in w2, "chaos.rejoin_delay_s never applied"

# shrink-and-continue really federated (epoch 1 ran at world 3): some
# worker joined a rank/3 seat
joined3 = any(
    "/3 (coordinator" in (out / f"worker_{i}.log").read_text()
    for i in range(4)
)
assert joined3, "no worker ever joined a world-3 epoch"

# the reformation barrier fired (workers left for reform, not crash)
reforms = sum(
    (out / f"worker_{i}.log").read_text().count("for reformation")
    for i in range(4)
)
assert reforms >= 3, f"expected a world-wide reformation, saw {reforms}"

# 4. the run FINISHED at the full world: the server trained the final
# round and the final evaluation ran
w0 = (out / "worker_0.log").read_text()
final_rounds = set()
evaled = False
for line in w0.splitlines():
    if '"training_loss"' in line:
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            continue
        final_rounds.add(int(rec["round"]))
        if rec.get("auc") is not None or rec.get("valid_auc") is not None:
            evaled = True
assert (rounds - 1) in final_rounds, sorted(final_rounds)
assert evaled, "the final evaluation never ran"
print("[elastic-smoke] counters + logs match the script")
PY

echo "[elastic-smoke] OK"
