#!/bin/bash
# Elastic-federation smoke (ISSUE-12 acceptance), CPU-only:
#
#   A 4-process gloo world under elastic membership
#   (fedrec_tpu.parallel.membership) loses one peer to a chaos kill
#   mid-run and must
#
#     1. SHRINK-AND-CONTINUE: the survivors re-form as membership epoch 1
#        at world 3 and keep federating (NOT 4 standalone forks — the
#        pre-elastic failure mode);
#     2. REJOIN: the killed peer's supervisor respawns it (held off by
#        chaos.rejoin_delay_s so the shrink is observable first); its
#        join knocks on the healthy epoch, the server broadcasts the
#        reformation at a round boundary, and epoch 2 re-forms at
#        world 4;
#     3. FINISH: the full-complement world completes every round and the
#        final evaluation runs;
#     4. ACCOUNT: the membership service's counters match the script —
#        exactly one shrink, exactly one rejoin, epoch history
#        world 4 -> 3 -> 4;
#     5. FLEET (ISSUE-13 acceptance): the per-worker obs artifacts +
#        round-cadence telemetry pushes (collector riding the membership
#        port) merge into ONE Perfetto trace whose per-worker tracks show
#        the kill -> shrink -> rejoin sequence as membership instants,
#        and `fedrec-obs fleet` names a critical-path worker for every
#        round — from the offline worker_* merge AND the collector dir.
#
#   scripts/elastic_smoke.sh     # or: make elastic-smoke
#
# Artifacts land under /tmp/fedrec_elastic_smoke for inspection.
set -euo pipefail
cd "$(dirname "$0")/.."

OUT=${ELASTIC_SMOKE_DIR:-/tmp/fedrec_elastic_smoke}
rm -rf "$OUT"
mkdir -p "$OUT"

MPORT=$(python - <<'PY'
import socket
s = socket.socket(); s.bind(("127.0.0.1", 0)); print(s.getsockname()[1]); s.close()
PY
)

ROUNDS=10

# ------------------------------------------------ the membership service
env -u PALLAS_AXON_POOL_IPS \
    PYTHONPATH="$PWD${PYTHONPATH:+:$PYTHONPATH}" \
    python -m fedrec_tpu.parallel.membership "127.0.0.1:$MPORT" \
    --target-world 4 \
    --obs-dir "$OUT/obs/worker_membership" \
    --telemetry-dir "$OUT/pushed" \
    > "$OUT/membership.log" 2>&1 &
MEM_PID=$!
cleanup() { kill "$MEM_PID" 2>/dev/null || true; }
trap cleanup EXIT
sleep 1

# --------------------------------------------------- 4 supervised workers
run_worker() {
    env -u PALLAS_AXON_POOL_IPS -u XLA_FLAGS JAX_PLATFORMS=cpu \
        FEDREC_SUPERVISE_MAX=12 \
        PYTHONPATH="$PWD${PYTHONPATH:+:$PYTHONPATH}" \
        python -m fedrec_tpu.cli.coordinator "$ROUNDS" 8 1 \
        --supervise \
        --membership "127.0.0.1:$MPORT" \
        --num-processes 4 --process-id "$1" \
        --synthetic --synthetic-train 960 --synthetic-news 64 \
        --clients 1 --server-trains \
        --collective-timeout 15 \
        --set model.bert_hidden=48 --set data.max_his_len=10 \
        --set data.max_title_len=12 --set model.news_dim=32 \
        --set model.num_heads=4 --set model.head_dim=8 \
        --set model.query_dim=16 \
        --set "train.snapshot_dir=$OUT/d$1" \
        --set "train.eval_every=$ROUNDS" \
        --set fed.weight_by_samples=true \
        --set optim.user_lr=0.001 --set optim.news_lr=0.001 \
        --set chaos.enabled=true \
        --set chaos.kill_round=2 --set chaos.kill_process=2 \
        --set chaos.rejoin_delay_s=15 \
        --set fed.elastic.lease_ms=5000 \
        --set fed.elastic.heartbeat_ms=1000 \
        --set fed.elastic.formation_grace_ms=6000 \
        --set "obs.dir=$OUT/obs" \
        --set "obs.fleet.collector=127.0.0.1:$MPORT" \
        > "$OUT/worker_$1.log" 2>&1
}

PIDS=()
for pid in 0 1 2 3; do
    run_worker "$pid" & PIDS+=($!)
done
FAIL=0
for i in 0 1 2 3; do
    wait "${PIDS[$i]}" || { echo "[elastic-smoke] worker $i FAILED"; FAIL=1; }
done
if [ "$FAIL" -ne 0 ]; then
    echo "[elastic-smoke] worker logs:"
    tail -n 40 "$OUT"/worker_*.log
    exit 1
fi

# --------------------------------------------------------- the assertions
env -u PALLAS_AXON_POOL_IPS \
    PYTHONPATH="$PWD${PYTHONPATH:+:$PYTHONPATH}" \
    OUT="$OUT" MPORT="$MPORT" ROUNDS="$ROUNDS" \
    python - <<'PY'
import json
import os
from pathlib import Path

from fedrec_tpu.parallel.membership import MembershipClient

out = Path(os.environ["OUT"])
rounds = int(os.environ["ROUNDS"])
st = MembershipClient(
    f"127.0.0.1:{os.environ['MPORT']}", worker_id="_smoke"
).status()
print("[elastic-smoke] membership status:", json.dumps(st))
hist = [h["world"] for h in st["epoch_history"]]

# 1. the initial epoch formed at the full complement
assert hist and hist[0] == 4, hist
# 2. shrink-and-continue: exactly one shrink, to world 3
assert st["shrinks"] == 1, st
assert 3 in hist, hist
# 3. rejoin: exactly one, and the world grew back to 4
assert st["rejoins"] == 1, st
assert hist[-1] == 4, hist
assert hist == [4, 3, 4], hist
# the dead peer's lease expired exactly once
assert st["lease_misses"] >= 1, st

w2 = (out / "worker_2.log").read_text()
assert "dying at round 2" in w2, "the chaos kill never fired"
assert w2.count("dying at round 2") == 1, "marker guard failed"
assert "holding off its rejoin" in w2, "chaos.rejoin_delay_s never applied"

# shrink-and-continue really federated (epoch 1 ran at world 3): some
# worker joined a rank/3 seat
joined3 = any(
    "/3 (coordinator" in (out / f"worker_{i}.log").read_text()
    for i in range(4)
)
assert joined3, "no worker ever joined a world-3 epoch"

# the reformation barrier fired (workers left for reform, not crash)
reforms = sum(
    (out / f"worker_{i}.log").read_text().count("for reformation")
    for i in range(4)
)
assert reforms >= 3, f"expected a world-wide reformation, saw {reforms}"

# 4. the run FINISHED at the full world: the server trained the final
# round and the final evaluation ran
w0 = (out / "worker_0.log").read_text()
final_rounds = set()
evaled = False
for line in w0.splitlines():
    if '"training_loss"' in line:
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            continue
        final_rounds.add(int(rec["round"]))
        if (rec.get("auc") is not None or rec.get("val_auc") is not None
                or rec.get("valid_auc") is not None):
            evaled = True
assert (rounds - 1) in final_rounds, sorted(final_rounds)
assert evaled, "the final evaluation never ran"
print("[elastic-smoke] counters + logs match the script")
PY

# ------------------------------------------------------- [5] the fleet leg
echo "[elastic-smoke] fleet leg: merged trace + critical-path report"
obs_cli() {
    env -u PALLAS_AXON_POOL_IPS \
        PYTHONPATH="$PWD${PYTHONPATH:+:$PYTHONPATH}" \
        python -m fedrec_tpu.cli.obs "$@"
}
obs_cli fleet "$OUT/obs" > "$OUT/fleet_report.txt"
obs_cli fleet "$OUT/obs" --json > "$OUT/fleet_report.json"
obs_cli fleet-trace "$OUT/obs" -o "$OUT/fleet_trace.json"
obs_cli fleet "$OUT/pushed" --json > "$OUT/fleet_pushed.json"

env -u PALLAS_AXON_POOL_IPS \
    PYTHONPATH="$PWD${PYTHONPATH:+:$PYTHONPATH}" \
    OUT="$OUT" ROUNDS="$ROUNDS" \
    python - <<'PY'
import json
import os
from pathlib import Path

out = Path(os.environ["OUT"])
rounds = int(os.environ["ROUNDS"])

# -- the offline worker_* merge: every worker + the service discovered
rep = json.loads((out / "fleet_report.json").read_text())
workers = set(rep["workers"])
assert {"0", "1", "2", "3", "membership"} <= workers, workers
assert rep["workers"]["membership"]["role"] == "membership_service"

# -- membership timeline: kill -> shrink -> rejoin reads off the report
hist = [h["world"] for h in rep["membership"]["epoch_history"]]
assert hist == [4, 3, 4], hist
assert rep["membership"]["shrinks"] == 1, rep["membership"]
assert rep["membership"]["rejoins"] == 1, rep["membership"]

# -- a named critical-path worker for EVERY round (the acceptance bar)
by_round = {r["round"]: r for r in rep["rounds"]}
for r in range(rounds):
    assert r in by_round, f"round {r} missing from the fleet report"
    row = by_round[r]
    assert row["critical_worker"] in {"0", "1", "2", "3"}, row
    assert row["round_ms"] > 0, row
assert rep["critical_path"], "no times-on-critical-path totals"

# -- the merged trace: one doc, >= 5 tracks, kill/shrink/rejoin instants
doc = json.loads((out / "fleet_trace.json").read_text())
assert len(doc["otherData"]["workers"]) >= 5, doc["otherData"]["workers"]
evs = [e for e in doc["traceEvents"] if e.get("ph") != "M"]
ts = [e["ts"] for e in evs]
assert ts == sorted(ts), "merged trace ts not monotonic"
names = [e["name"] for e in evs]
formed = [e for e in evs if e["name"] == "membership_epoch_formed"]
assert [f["args"]["world"] for f in formed] == [4, 3, 4], formed
expired = [e for e in evs if e["name"] == "membership_lease_expired"]
assert any(e["args"]["worker"] == "2" for e in expired), \
    "the chaos-killed worker's lease expiry is not in the merged trace"
assert "membership_worker_join" in names
assert "fed_round" in names
# per-worker tracks really carry the correlation keys
fr = [e for e in evs if e["name"] == "fed_round"]
assert {e["args"].get("worker") for e in fr} >= {"0", "1", "3"}, \
    "fed_round spans lost their worker labels"

# -- the collector got round-cadence pushes and renders the same story
pushed = json.loads((out / "fleet_pushed.json").read_text())
assert {"0", "1", "2", "3"} <= set(pushed["workers"]), pushed["workers"]
assert pushed["rounds"], "no rounds in the collector-side report"
# the killed worker's pre-kill rounds survived ONLY via pushes: its
# epoch-0 spans must be present in the collector merge
w2_rounds = {r["round"] for r in pushed["rounds"] if "2" in r["workers"]}
assert 0 in w2_rounds or 1 in w2_rounds, \
    "worker 2's pre-kill rounds never reached the collector"

# -- counter continuity: a respawned worker's totals resumed (monotone)
from fedrec_tpu.obs.report import load_jsonl, snapshot_value
_, snaps = load_jsonl(out / "obs" / "worker_2" / "metrics.jsonl")
totals = [
    v for s in snaps
    if (v := snapshot_value(s, "train.rounds_total")) is not None
]
assert totals == sorted(totals), f"worker 2 totals not monotone: {totals}"
assert totals and totals[-1] >= rounds - 2, totals

print("[elastic-smoke] fleet leg OK "
      f"({len(rep['rounds'])} rounds attributed, "
      f"{len(workers)} workers merged)")
PY

echo "[elastic-smoke] OK"
