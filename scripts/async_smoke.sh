#!/bin/bash
# Buffered-async aggregation smoke (agg.mode=async across processes),
# CPU-only:
#
#   An agg.server commit authority (quorum 3 of world 4) + 4 async
#   workers, each a single-process Trainer pushing round deltas over the
#   fleet wire — worker 3 chaos-delayed 4s per push. Must prove:
#
#     1. QUORUM COMMIT: the global advances one version per round on the
#        3 on-time workers alone — the straggler is still sleeping when
#        the commit fires (>= ROUNDS commits total);
#     2. LATE FOLD: the straggler's delayed contribution lands in the
#        buffer and folds staleness-weighted into a LATER commit
#        (late_folds >= 1), never dropped while within agg.staleness_cap;
#     3. GATE -> ~0: the straggler's marginal commit gate (the async
#        analogue of the barrier's critical-path gate_ms) stays ~0 — a
#        barrier deployment would have charged it the full 4s straggle
#        every round;
#     4. FLEET: `fedrec-obs fleet` merges the commit authority's obs
#        artifacts with the workers' and renders the Aggregation panel
#        (commits / late folds / per-worker gate before-vs-after) AND
#        the Wire panel (per-edge RTT/offsets, the queue/wire/fold
#        commit decomposition, the straggler's push edge on the table);
#        `fedrec-obs fleet-trace` merges a trace whose wire flow arrows
#        causally link a worker's push into the authority's commit and
#        the commit into a worker's adoption — across process tracks;
#     5. PERSIST: the pending buffer survives on disk (agg_buffer.npz in
#        --state-dir) after the service stops;
#     6. FLEET WATCH: a live telemetry collector (--watch) receives
#        every worker's round pushes; its fleet-level watch rules must
#        catch worker 3 as a persistent straggler from push inter-arrival
#        gaps alone (the chaos sleep sits at the push boundary, OUTSIDE
#        train.round_seconds) and write a firing fleet:straggler:3 alert
#        record to the collector's worker_fleet log;
#     7. COUNTSKETCH: a second 2-worker cluster pushes
#        fed.dcn_compress=countsketch — the commit authority folds the
#        raw sketches in sketch space (version still advances one per
#        round) and the measured per-push wire bytes land well under the
#        dense leg's (the aggregated-end compression claim, on the real
#        wire).
#
#   scripts/async_smoke.sh     # or: make async-smoke
#
# Artifacts land under /tmp/fedrec_async_smoke for inspection.
set -euo pipefail
cd "$(dirname "$0")/.."

OUT=${ASYNC_SMOKE_DIR:-/tmp/fedrec_async_smoke}
rm -rf "$OUT"
mkdir -p "$OUT"

APORT=$(python - <<'PY'
import socket
s = socket.socket(); s.bind(("127.0.0.1", 0)); print(s.getsockname()[1]); s.close()
PY
)
CPORT=$(python - <<'PY'
import socket
s = socket.socket(); s.bind(("127.0.0.1", 0)); print(s.getsockname()[1]); s.close()
PY
)

ROUNDS=3
STRAGGLE_MS=4000

# --------------------------------------------------- the commit authority
env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
    PYTHONPATH="$PWD${PYTHONPATH:+:$PYTHONPATH}" \
    python -m fedrec_tpu.agg.server "127.0.0.1:$APORT" \
    --quorum 3 --world 4 \
    --obs-dir "$OUT/obs/worker_aggserver" \
    --state-dir "$OUT/aggstate" \
    > "$OUT/aggserver.log" 2>&1 &
AGG_PID=$!

# --------------------------- the live telemetry collector (fleet watch):
# --straggler-evals 2 because 3 rounds give worker 3 only 2 push gaps —
# both breach (4s sleep vs the trio's sub-second cadence), so the rule
# confirms and fires on the last push. JAX is never imported here.
PYTHONPATH="$PWD${PYTHONPATH:+:$PYTHONPATH}" \
    python -m fedrec_tpu.obs.fleet "127.0.0.1:$CPORT" \
    --dir "$OUT/collector" --watch --straggler-evals 2 \
    > "$OUT/collector.log" 2>&1 &
COLL_PID=$!
cleanup() { kill "$AGG_PID" "$COLL_PID" 2>/dev/null || true; }
trap cleanup EXIT
sleep 1

# ------------------------------------------------------- 4 async workers
run_worker() {
    local extra=()
    if [ "$1" = 3 ]; then
        # the scripted straggler: sleeps at the push boundary, so every
        # commit it could have gated fires without it
        extra=(--set chaos.enabled=true --set "chaos.straggle_ms=$STRAGGLE_MS")
    fi
    env -u PALLAS_AXON_POOL_IPS -u XLA_FLAGS JAX_PLATFORMS=cpu \
        PYTHONPATH="$PWD${PYTHONPATH:+:$PYTHONPATH}" \
        python -m fedrec_tpu.cli.run "$ROUNDS" 8 10 \
        --agg-server "127.0.0.1:$APORT" --worker-id "$1" \
        --strategy param_avg --clients 1 \
        --synthetic --synthetic-train 256 --synthetic-news 64 \
        --set model.bert_hidden=48 --set data.max_his_len=10 \
        --set data.max_title_len=12 --set model.news_dim=32 \
        --set model.num_heads=4 --set model.head_dim=8 \
        --set model.query_dim=16 \
        --set "train.snapshot_dir=$OUT/d$1" \
        --set "train.eval_every=$ROUNDS" \
        --set optim.user_lr=0.001 --set optim.news_lr=0.001 \
        --set "obs.dir=$OUT/obs" \
        --set "obs.fleet.collector=127.0.0.1:$CPORT" \
        "${extra[@]}" \
        > "$OUT/worker_$1.log" 2>&1
}

PIDS=()
for wid in 0 1 2 3; do
    run_worker "$wid" & PIDS+=($!)
done
FAIL=0
for i in 0 1 2 3; do
    wait "${PIDS[$i]}" || { echo "[async-smoke] worker $i FAILED"; FAIL=1; }
done
if [ "$FAIL" -ne 0 ]; then
    echo "[async-smoke] logs:"
    tail -n 40 "$OUT"/worker_*.log "$OUT/aggserver.log"
    exit 1
fi

# ------------------------------------------- [1-3] commit-log assertions
env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
    PYTHONPATH="$PWD${PYTHONPATH:+:$PYTHONPATH}" \
    OUT="$OUT" APORT="$APORT" ROUNDS="$ROUNDS" STRAGGLE_MS="$STRAGGLE_MS" \
    python - <<'PY'
import json
import os

from fedrec_tpu.obs.fleet import request_json_line

out = os.environ["OUT"]
rounds = int(os.environ["ROUNDS"])
straggle_ms = float(os.environ["STRAGGLE_MS"])
st = request_json_line(
    "127.0.0.1", int(os.environ["APORT"]), {"cmd": "status"}, timeout_s=10.0
)
print("[async-smoke] aggserver status:", json.dumps(st))

# 1. quorum commit: one version per round from the on-time trio (the
# straggler's pushes can only ADD commits, never block one)
assert st["version"] >= rounds, st
assert {"0", "1", "2", "3"} <= set(st["workers"]), st
commits = st["commits"]
assert len(commits) == st["version"], commits
# every commit fired at exactly quorum (3 distinct pending) or more
assert all(c["quorum"] >= 3 for c in commits), commits

# 2. late fold: the straggler's delayed delta folded with staleness > 0
late = sum(c["late_folds"] for c in commits)
assert late >= 1, f"no late folds in {commits}"
assert sum(c["stale_drops"] for c in commits) == 0, \
    "a within-cap contribution was dropped"

# 3. gate -> ~0: worker 3 is charged (almost) nothing. The barrier
# would charge it ~straggle_ms EVERY round; async charges it only when
# it happens to close a quorum, a race window of one push (< half the
# straggle even then).
w3_gates = [c["gate_ms"] for c in commits if c["closer"] == "3"]
w3_total = sum(w3_gates)
assert w3_total < straggle_ms / 2, (
    f"straggler charged {w3_total:.0f} ms across {len(w3_gates)} commit(s)"
)
barrier_cost = straggle_ms * rounds
print(f"[async-smoke] straggler gate: {w3_total:.0f} ms async vs "
      f"~{barrier_cost:.0f} ms the barrier would have charged")

# bank the dense per-push wire bytes for the countsketch leg's comparison
pushes = st.get("push_counts") or {}
per_push = {
    w: st["push_bytes"][w] / max(pushes.get(w, 1), 1)
    for w in st.get("push_bytes", {})
}
assert per_push, f"server counted no push bytes: {st}"
with open(os.path.join(out, "push_bytes_dense.json"), "w") as f:
    json.dump(per_push, f)
PY

# straggler really straggled (the chaos knob engaged)
grep -q "straggling" "$OUT/worker_3.log" \
    || { echo "[async-smoke] worker 3 never straggled"; exit 1; }

# ---------------------------------------- [6] fleet watch at the collector:
# the persistent-straggler rule must have caught worker 3 from its push
# cadence alone and written a firing alert record to the fleet log
FLEET_LOG="$OUT/collector/worker_fleet/metrics.jsonl"
test -s "$FLEET_LOG" \
    || { echo "[async-smoke] collector wrote no fleet watch log"; \
         tail -20 "$OUT/collector.log"; exit 1; }
grep '"kind": "alert"' "$FLEET_LOG" | grep '"key": "fleet:straggler:3"' \
    | grep -q '"event": "firing"' \
    || { echo "[async-smoke] fleet rule never fired on the straggler"; \
         cat "$FLEET_LOG"; exit 1; }
# ...and stayed quiet about the on-time trio
if grep '"event": "firing"' "$FLEET_LOG" \
    | grep -qE '"key": "fleet:straggler:[012]"'; then
    echo "[async-smoke] fleet rule flagged an on-time worker"; exit 1
fi
echo "[async-smoke] fleet watch caught the straggler:"
grep '"key": "fleet:straggler:3"' "$FLEET_LOG" | head -1
kill -TERM "$COLL_PID" 2>/dev/null || true
wait "$COLL_PID" 2>/dev/null || true

# ------------------------------------------------ stop the service (flushes
# its obs artifacts + the buffer sidecar on the way down)
kill -TERM "$AGG_PID"
wait "$AGG_PID" 2>/dev/null || true

# ---------------------------------------------------- [5] buffer persisted
test -s "$OUT/aggstate/agg_buffer.npz" \
    || { echo "[async-smoke] no persisted buffer sidecar"; exit 1; }

# ------------------------------------------------------- [4] the fleet leg
env -u PALLAS_AXON_POOL_IPS \
    PYTHONPATH="$PWD${PYTHONPATH:+:$PYTHONPATH}" \
    python -m fedrec_tpu.cli.obs fleet "$OUT/obs" > "$OUT/fleet_report.txt"
env -u PALLAS_AXON_POOL_IPS \
    PYTHONPATH="$PWD${PYTHONPATH:+:$PYTHONPATH}" \
    python -m fedrec_tpu.cli.obs fleet "$OUT/obs" --json \
    > "$OUT/fleet_report.json"

env -u PALLAS_AXON_POOL_IPS \
    PYTHONPATH="$PWD${PYTHONPATH:+:$PYTHONPATH}" \
    OUT="$OUT" ROUNDS="$ROUNDS" STRAGGLE_MS="$STRAGGLE_MS" \
    python - <<'PY'
import json
import os
from pathlib import Path

out = Path(os.environ["OUT"])
rounds = int(os.environ["ROUNDS"])
straggle_ms = float(os.environ["STRAGGLE_MS"])

rep = json.loads((out / "fleet_report.json").read_text())
workers = set(rep["workers"])
assert {"0", "1", "2", "3", "aggserver"} <= workers, workers

agg = rep.get("agg") or {}
assert "aggserver" in agg, f"no agg section for the commit authority: {agg}"
srv = agg["aggserver"]
assert srv.get("role") == "agg_server", srv
assert srv.get("commits", 0) >= rounds, srv
assert srv.get("late_folds", 0) >= 1, srv
gates = srv.get("worker_gate_ms") or {}
assert "3" in gates, gates
assert gates["3"] < straggle_ms / 2, (
    f"fleet report charges the straggler {gates['3']:.0f} ms"
)
# the workers' own push accounting made it into the merge
pushed = [w for w, aw in agg.items() if aw.get("pushes", 0) >= rounds]
assert len(pushed) >= 4, f"workers with >= {rounds} pushes: {pushed}"

text = (out / "fleet_report.txt").read_text()
assert "## Aggregation" in text, "no Aggregation panel in the fleet text"
assert "gate_ms before" in text, "no before/after gate panel"
print("[async-smoke] fleet leg OK "
      f"(straggler gate {gates['3']:.0f} ms in the merged report)")
PY

# ------------------------------------------------- [4b] the wire leg:
# the merged trace carries cross-process flow arrows (a worker's push
# causally linked into the authority's commit, the commit linked into a
# worker's adoption) and the fleet report carries the Wire panel with
# the chaos-delayed worker's edge on it
env -u PALLAS_AXON_POOL_IPS \
    PYTHONPATH="$PWD${PYTHONPATH:+:$PYTHONPATH}" \
    python -m fedrec_tpu.cli.obs fleet-trace "$OUT/obs" \
    -o "$OUT/fleet_trace.json"

env -u PALLAS_AXON_POOL_IPS \
    PYTHONPATH="$PWD${PYTHONPATH:+:$PYTHONPATH}" \
    OUT="$OUT" \
    python - <<'PY'
import json
import os
from collections import defaultdict
from pathlib import Path

out = Path(os.environ["OUT"])
doc = json.loads((out / "fleet_trace.json").read_text())
events = doc["traceEvents"]
pid_of = doc["otherData"]["workers"]          # wid -> pid
agg_pid = pid_of["aggserver"]
worker_pids = {p for w, p in pid_of.items() if w != "aggserver"}

# cross-process flow arrows survived the merge
flows = [e for e in events if e.get("cat") == "wire"]
assert flows, "no wire flow events in the merged trace"
by_id = defaultdict(list)
for e in flows:
    by_id[e["id"]].append(e)
cross = {i for i, evs in by_id.items() if len({e["pid"] for e in evs}) >= 2}
assert cross, "no flow id crosses two process tracks"

# a worker push linked INTO the authority (start on a worker pid,
# finish on the agg pid), and a commit linked OUT to an adopting worker
push_arrows = [
    i for i, evs in by_id.items()
    if any(e["ph"] == "s" and e["pid"] in worker_pids for e in evs)
    and any(e["ph"] == "f" and e["pid"] == agg_pid for e in evs)
]
adopt_arrows = [
    i for i, evs in by_id.items()
    if any(e["ph"] == "s" and e["pid"] == agg_pid for e in evs)
    and any(e["ph"] == "f" and e["pid"] in worker_pids for e in evs)
]
assert push_arrows, "no flow arrow from a worker push into the authority"
assert adopt_arrows, "no flow arrow from the authority out to an adoption"
commits = [e for e in events
           if e.get("name") == "agg.commit" and e.get("pid") == agg_pid]
adopts = [e for e in events if e.get("name") == "agg.adopt"]
assert commits, "no agg.commit spans on the authority's track"
assert adopts, "no agg.adopt spans on any worker track"

# the Wire panel made it into the fleet report, straggler edge included
rep = json.loads((out / "fleet_report.json").read_text())
wire = rep.get("wire") or {}
edges = wire.get("edges") or {}
w3 = edges.get("3") or []
assert any(e.get("peer") == "aggserver" and e.get("op") == "push"
           for e in w3), f"no worker-3 push edge in the Wire panel: {edges}"
assert wire.get("offsets_ms"), "no per-edge clock offsets in the report"
decomp = wire.get("commit_decomposition") or {}
assert decomp.get("queue_ms") is not None, decomp
assert decomp.get("edges"), decomp

text = (out / "fleet_report.txt").read_text()
assert "## Wire" in text, "no Wire panel in the fleet text"
assert "slowest edge" in text, "no slowest-edge callout"
print(f"[async-smoke] wire leg OK ({len(cross)} cross-process flow "
      f"arrow(s), {len(push_arrows)} push->commit, "
      f"{len(adopt_arrows)} commit->adopt)")
PY

# -------------------------------------------- [7] the countsketch leg:
# a fresh 2-worker cluster pushing sketch-coded deltas — commits advance
# and the wire bytes shrink ~1/sketch_width vs the dense leg
SPORT=$(python - <<'PY'
import socket
s = socket.socket(); s.bind(("127.0.0.1", 0)); print(s.getsockname()[1]); s.close()
PY
)
env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
    PYTHONPATH="$PWD${PYTHONPATH:+:$PYTHONPATH}" \
    python -m fedrec_tpu.agg.server "127.0.0.1:$SPORT" \
    --quorum 2 --world 2 --sketch-seed 0 \
    --obs-dir "$OUT/obs_sk/worker_aggserver" \
    --state-dir "$OUT/aggstate_sk" \
    > "$OUT/aggserver_sk.log" 2>&1 &
SK_PID=$!
cleanup() { kill "$AGG_PID" "$COLL_PID" "$SK_PID" 2>/dev/null || true; }
sleep 1

run_sketch_worker() {
    env -u PALLAS_AXON_POOL_IPS -u XLA_FLAGS JAX_PLATFORMS=cpu \
        PYTHONPATH="$PWD${PYTHONPATH:+:$PYTHONPATH}" \
        python -m fedrec_tpu.cli.run "$ROUNDS" 8 10 \
        --agg-server "127.0.0.1:$SPORT" --worker-id "$1" \
        --strategy param_avg --clients 1 \
        --synthetic --synthetic-train 256 --synthetic-news 64 \
        --set model.bert_hidden=48 --set data.max_his_len=10 \
        --set data.max_title_len=12 --set model.news_dim=32 \
        --set model.num_heads=4 --set model.head_dim=8 \
        --set model.query_dim=16 \
        --set fed.dcn_compress=countsketch \
        --set fed.dcn_sketch_width=0.1 --set fed.dcn_sketch_seed=0 \
        --set "train.snapshot_dir=$OUT/sk$1" \
        --set "train.eval_every=$ROUNDS" \
        --set optim.user_lr=0.001 --set optim.news_lr=0.001 \
        --set "obs.dir=$OUT/obs_sk" \
        > "$OUT/worker_sk_$1.log" 2>&1
}

SK_PIDS=()
for wid in 0 1; do
    run_sketch_worker "$wid" & SK_PIDS+=($!)
done
SK_FAIL=0
for i in 0 1; do
    wait "${SK_PIDS[$i]}" || { echo "[async-smoke] sketch worker $i FAILED"; SK_FAIL=1; }
done
if [ "$SK_FAIL" -ne 0 ]; then
    echo "[async-smoke] sketch leg logs:"
    tail -n 40 "$OUT"/worker_sk_*.log "$OUT/aggserver_sk.log"
    exit 1
fi

env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
    PYTHONPATH="$PWD${PYTHONPATH:+:$PYTHONPATH}" \
    OUT="$OUT" SPORT="$SPORT" ROUNDS="$ROUNDS" \
    python - <<'PY'
import json
import os

from fedrec_tpu.obs.fleet import request_json_line

out = os.environ["OUT"]
rounds = int(os.environ["ROUNDS"])
st = request_json_line(
    "127.0.0.1", int(os.environ["SPORT"]), {"cmd": "status"}, timeout_s=10.0
)
print("[async-smoke] sketch aggserver status:", json.dumps(st))

# sketch-coded pushes still commit: one version per round at quorum 2
assert st["version"] >= rounds, st
assert all(c["quorum"] >= 2 for c in st["commits"]), st["commits"]

# the wire shrank: per-push bytes well under the dense leg's. Width 0.1
# prices ~10x on big towers; the smoke model's many tiny leaves round
# m = max(1, round(width*n)) up and pay npz framing per leaf, so ~4-5x
# is the honest figure here — 4x is the floor only a broken encoder
# misses (base64 framing is identical on both legs).
dense = json.load(open(os.path.join(out, "push_bytes_dense.json")))
dense_per = sum(dense.values()) / len(dense)
counts = st["push_counts"]
sk_per = sum(st["push_bytes"][w] / max(counts.get(w, 1), 1)
             for w in st["push_bytes"]) / len(st["push_bytes"])
assert sk_per * 4 < dense_per, (
    f"countsketch pushes {sk_per:.0f} B/push vs dense {dense_per:.0f} "
    "B/push — expected ~10x smaller"
)
print(f"[async-smoke] countsketch uplink {sk_per:.0f} B/push vs dense "
      f"{dense_per:.0f} B/push ({dense_per / sk_per:.1f}x smaller)")
PY

kill -TERM "$SK_PID"
wait "$SK_PID" 2>/dev/null || true

# ------------------------------------------- [8] the fault-injection leg:
# a fresh 2-worker cluster where the WIRE itself misbehaves — worker 0
# dials the authority through an in-process chaos proxy that drops 30%
# of its connections and tears two mid-run windows mid-message; worker 1's
# proxy DUPLICATES every push (the lost-ack re-delivery case) — and the
# authority is SIGTERM-killed mid-run for a 10 s outage, then respawned
# from its state sidecars on the same port. Must prove: both workers
# still exit 0 (parked pushes, stale progress, re-hello on the
# incarnation bump), the respawn resumes the committed global, the
# commit version keeps advancing past the pre-kill version (no lost
# commit), and every duplicated delivery is detected by the push ledger
# instead of double-folded.
FPORT=$(python - <<'PY'
import socket
s = socket.socket(); s.bind(("127.0.0.1", 0)); print(s.getsockname()[1]); s.close()
PY
)
ROUNDS_F=12
spawn_fault_authority() {
    env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
        PYTHONPATH="$PWD${PYTHONPATH:+:$PYTHONPATH}" \
        python -m fedrec_tpu.agg.server "127.0.0.1:$FPORT" \
        --quorum 2 --world 2 \
        --obs-dir "$OUT/obs_fault/worker_aggserver" \
        --state-dir "$OUT/aggstate_fault" \
        >> "$OUT/aggserver_fault.log" 2>&1 &
    FAULT_PID=$!
}
spawn_fault_authority
cleanup() { kill "$AGG_PID" "$COLL_PID" "$SK_PID" "$FAULT_PID" 2>/dev/null || true; }
sleep 1

run_fault_worker() {
    local faults="$2" seed="$3"
    env -u PALLAS_AXON_POOL_IPS -u XLA_FLAGS JAX_PLATFORMS=cpu \
        PYTHONPATH="$PWD${PYTHONPATH:+:$PYTHONPATH}" \
        python -m fedrec_tpu.cli.run "$ROUNDS_F" 8 10 \
        --agg-server "127.0.0.1:$FPORT" --worker-id "$1" \
        --strategy param_avg --clients 1 \
        --synthetic --synthetic-train 256 --synthetic-news 64 \
        --set model.bert_hidden=48 --set data.max_his_len=10 \
        --set data.max_title_len=12 --set model.news_dim=32 \
        --set model.num_heads=4 --set model.head_dim=8 \
        --set model.query_dim=16 \
        --set "train.snapshot_dir=$OUT/f$1" \
        --set "train.eval_every=$ROUNDS_F" \
        --set optim.user_lr=0.001 --set optim.news_lr=0.001 \
        --set "obs.dir=$OUT/obs_fault" \
        --set chaos.enabled=true --set chaos.straggle_ms=1200 \
        --set "chaos.wire_faults=$faults" --set "chaos.wire_seed=$seed" \
        --set agg.worker_timeout_s=6 --set agg.worker_global_wait_s=6 \
        --set agg.worker_rpc_attempts=6 \
        > "$OUT/worker_f$1.log" 2>&1
}

F_PIDS=()
run_fault_worker 0 'drop@*:0.3,tear@10-14,tear@20-24' 1 & F_PIDS+=($!)
run_fault_worker 1 'dup@*' 2 & F_PIDS+=($!)

# wait for the first commits, then SIGTERM the authority mid-run
V_KILL=$(env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
    PYTHONPATH="$PWD${PYTHONPATH:+:$PYTHONPATH}" \
    FPORT="$FPORT" python - <<'PY'
import os
import time

from fedrec_tpu.obs.fleet import request_json_line

deadline = time.monotonic() + 120
v = -1
while time.monotonic() < deadline:
    try:
        st = request_json_line(
            "127.0.0.1", int(os.environ["FPORT"]), {"cmd": "status"},
            timeout_s=5.0,
        )
        v = int(st["version"])
        if v >= 2:
            break
    except (OSError, ValueError):
        pass
    time.sleep(0.3)
print(v)
PY
)
[ "$V_KILL" -ge 2 ] \
    || { echo "[async-smoke] fault leg never reached v2 before the kill"; \
         tail -n 40 "$OUT"/worker_f*.log "$OUT/aggserver_fault.log"; exit 1; }
kill -TERM "$FAULT_PID"
wait "$FAULT_PID" 2>/dev/null || true
echo "[async-smoke] fault leg: authority killed at v$V_KILL, 10 s outage"
sleep 10
spawn_fault_authority
grep -q "resumed committed global" "$OUT/aggserver_fault.log" || sleep 2

F_FAIL=0
for i in 0 1; do
    wait "${F_PIDS[$i]}" || { echo "[async-smoke] fault worker $i FAILED"; F_FAIL=1; }
done
if [ "$F_FAIL" -ne 0 ]; then
    echo "[async-smoke] fault leg logs:"
    tail -n 40 "$OUT"/worker_f*.log "$OUT/aggserver_fault.log"
    exit 1
fi

# the respawn resumed the persisted committed global (not a cold init)
grep -q "resumed committed global" "$OUT/aggserver_fault.log" \
    || { echo "[async-smoke] respawned authority never resumed the sidecar"; \
         cat "$OUT/aggserver_fault.log"; exit 1; }

env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
    PYTHONPATH="$PWD${PYTHONPATH:+:$PYTHONPATH}" \
    OUT="$OUT" FPORT="$FPORT" V_KILL="$V_KILL" \
    python - <<'PY'
import json
import os

from fedrec_tpu.obs.fleet import request_json_line

v_kill = int(os.environ["V_KILL"])
st = request_json_line(
    "127.0.0.1", int(os.environ["FPORT"]), {"cmd": "status"}, timeout_s=10.0
)
print("[async-smoke] fault aggserver status:", json.dumps(st)[:400])

# no lost commit: the restored authority advertises incarnation 2 and the
# version kept advancing PAST the pre-kill version once the workers'
# parked pushes drained
assert st["incarnation"] == 2, st["incarnation"]
assert st["version"] > v_kill, (
    f"version stuck at v{st['version']} after restart at v{v_kill}"
)
assert all(c["quorum"] >= 2 for c in st["commits"]), st["commits"]

# no double-fold: worker 1's edge duplicated every push in flight — the
# ledger must have answered `duplicate` for the re-deliveries instead of
# folding them twice
assert st["push_dups"] >= 1, (
    f"dup@* edge produced no detected duplicates: {st['push_dups']}"
)
print(f"[async-smoke] fault leg OK (v{v_kill} -> v{st['version']} across "
      f"the outage, {st['push_dups']} duplicate push(es) detected, "
      "0 double-folded)")
PY

kill -TERM "$FAULT_PID"
wait "$FAULT_PID" 2>/dev/null || true

echo "[async-smoke] OK"
