#!/bin/bash
# Observability smoke (ISSUE-3 + ISSUE-4 acceptance scenarios), CPU-only:
#
#   1. a 2-round synthetic training run with obs.dir set (+ DP so the
#      epsilon gauge is live, + prefetch so queue health is live),
#   2. a short serve_load run with --obs-dir,
#   3. assert each produced the artifact trio — registry-snapshot JSONL,
#      a valid Perfetto/Chrome trace with >= 4 distinct span names, a
#      Prometheus exposition carrying serve p50/p99 + prefetch queue
#      depth + privacy.epsilon_spent — and that fedrec-obs renders both
#      into run reports,
#   4. a forced-NaN micro-run (inf lr for step 1): the numeric sentry
#      must abort the run, the flight recorder must dump the offending
#      batch + state + manifest + registry snapshot under
#      obs.dir/flightrec/, and `fedrec-obs replay` must reproduce the
#      non-finite step on CPU (exit 0 = REPRODUCED),
#   5. the model-quality smoke (scripts/quality_smoke.sh): sliced-eval
#      gauges + Quality report section, the store drift-probe leg, and
#      the forced quality-gate regression failure,
#   6. the perf leg: the training run of (1) carries obs.perf.enabled +
#      a capture window on round 1 — assert the Perf report section,
#      `fedrec-obs perf` exit 0, the capture-window trace landing inside
#      obs.dir with its metrics.jsonl pointer record, then the
#      perf-regression gate: bank a fresh baseline, pass a clean check,
#      and prove --demo-regression fails naming the lane,
#   7. the watch leg (scripts/watch_smoke.sh): a forced SLO breach must
#      fire and resolve, an unmeetable SLO must keep `fedrec-obs alerts`
#      / `tail --once` at exit 1, and the disabled path must leave zero
#      watch footprint.
#
#   scripts/obs_smoke.sh     # or: make obs-smoke
#
# Artifacts land under /tmp/fedrec_obs_smoke for inspection.
set -euo pipefail
cd "$(dirname "$0")/.."

OUT=${OBS_SMOKE_DIR:-/tmp/fedrec_obs_smoke}
rm -rf "$OUT"
mkdir -p "$OUT"

run() {
    env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
        XLA_FLAGS="--xla_force_host_platform_device_count=8" "$@"
}

echo "== [1/7] 2-round CPU training run (DP + prefetch) =="
run python -m fedrec_tpu.cli.run 2 16 2 --strategy param_avg --clients 8 \
    --synthetic --synthetic-train 512 --synthetic-news 128 \
    --mode joint --dp-epsilon 10 \
    --obs-dir "$OUT/train" \
    --set obs.perf.enabled=1 --set obs.perf.capture_rounds=1 \
    --set data.prefetch_batches=2 \
    --set model.news_dim=32 --set model.num_heads=4 --set model.head_dim=8 \
    --set model.query_dim=16 --set model.bert_hidden=48 \
    --set data.max_his_len=10 --set data.max_title_len=12 \
    --set train.snapshot_dir="$OUT/train_snap" --set train.eval_every=1 \
    --set train.eval_protocol=sampled > "$OUT/train.log" 2>&1 \
    || { tail -30 "$OUT/train.log"; exit 1; }

echo "== [2/7] serve_load run =="
run python benchmarks/serve_load.py --num-news 2000 --his-len 10 \
    --clients 4 --rate 50 --duration 2 --out obs_smoke_serve_load.json \
    --obs-dir "$OUT/serve" > "$OUT/serve.log" 2>&1 \
    || { tail -30 "$OUT/serve.log"; exit 1; }
rm -f benchmarks/obs_smoke_serve_load.json

echo "== [3/7] artifact assertions =="
for d in train serve; do
    for f in metrics.jsonl trace.json prometheus.txt; do
        [ -s "$OUT/$d/$f" ] || { echo "MISSING $OUT/$d/$f"; exit 1; }
    done
done

python - "$OUT" <<'EOF'
import json, sys
out = sys.argv[1]

for run in ("train", "serve"):
    doc = json.load(open(f"{out}/{run}/trace.json"))
    evs = doc["traceEvents"]
    names = {e["name"] for e in evs}
    assert len(names) >= 4, f"{run}: want >=4 span names, got {sorted(names)}"
    ts = [e["ts"] for e in evs]
    assert ts == sorted(ts), f"{run}: trace ts not monotonic"
    snaps = [json.loads(l) for l in open(f"{out}/{run}/metrics.jsonl")
             if '"registry_snapshot"' in l]
    assert snaps, f"{run}: no registry snapshot in metrics.jsonl"
    print(f"  {run}: {len(evs)} events, span names ok: {sorted(names)[:6]}...")

train_prom = open(f"{out}/train/prometheus.txt").read()
serve_prom = open(f"{out}/serve/prometheus.txt").read()
for needle, hay, which in (
    ("privacy.epsilon_spent", train_prom, "train"),
    ("data_prefetch_queue_depth", train_prom, "train"),
    ("serve_p50_ms", serve_prom, "serve"),
    ("serve_p99_ms", serve_prom, "serve"),
    ("serve_queue_depth", serve_prom, "serve"),
):
    assert needle in hay, f"{which} prometheus.txt missing {needle}"
print("  prometheus expositions carry p50/p99, queue depth, epsilon_spent")
EOF

echo "== run reports =="
python -m fedrec_tpu.cli.obs report "$OUT/train"
python -m fedrec_tpu.cli.obs report "$OUT/serve"

echo "== fleet leg (single-worker degenerate) =="
# fedrec-obs fleet/fleet-trace must degrade gracefully to one obs dir:
# every round attributed to worker 0, the merged trace valid Perfetto
python -m fedrec_tpu.cli.obs fleet "$OUT/train" --json > "$OUT/fleet.json"
python -m fedrec_tpu.cli.obs fleet-trace "$OUT/train" \
    -o "$OUT/fleet_trace.json" > /dev/null
python - "$OUT" <<'EOF'
import json, sys
out = sys.argv[1]
rep = json.load(open(f"{out}/fleet.json"))
assert set(rep["workers"]) == {"0"}, rep["workers"]
assert len(rep["rounds"]) == 2, rep.get("rounds")
assert all(r["critical_worker"] == "0" and r["gate_ms"] == 0.0
           for r in rep["rounds"]), rep["rounds"]
doc = json.load(open(f"{out}/fleet_trace.json"))
evs = [e for e in doc["traceEvents"] if e.get("ph") != "M"]
ts = [e["ts"] for e in evs]
assert ts == sorted(ts), "merged trace ts not monotonic"
assert any(e["name"] == "fed_round" and e["args"].get("worker") == "0"
           for e in evs), "fed_round spans lost their worker label"
print("  fleet: 2 rounds attributed to worker 0, merged trace valid")
EOF

echo "== [4/7] forced-NaN flight-recorder round-trip =="
# inf lr: the first optimizer update goes non-finite, the sentry trips,
# the run must ABORT (nonzero exit) after dumping forensics
if run python -m fedrec_tpu.cli.run 2 16 1000 --strategy param_avg --clients 8 \
    --synthetic --synthetic-train 256 --synthetic-news 64 --mode joint \
    --obs-dir "$OUT/nan" \
    --set optim.user_lr=inf \
    --set model.news_dim=32 --set model.num_heads=4 --set model.head_dim=8 \
    --set model.query_dim=16 --set model.bert_hidden=48 \
    --set data.max_his_len=10 --set data.max_title_len=12 \
    --set train.snapshot_dir="$OUT/nan_snap" --set train.eval_every=1000 \
    > "$OUT/nan.log" 2>&1; then
    echo "forced-NaN run exited 0 — the sentry did not abort"; exit 1
fi
grep -q "training-health trigger \[nonfinite\]" "$OUT/nan.log" \
    || { echo "no nonfinite trigger in nan.log"; tail -20 "$OUT/nan.log"; exit 1; }
for f in manifest.json state.msgpack registry.json table.npy batch_000.npz; do
    [ -s "$OUT/nan/flightrec/$f" ] || { echo "MISSING flightrec/$f"; exit 1; }
done
# the dump must replay deterministically on CPU and reproduce the flag
run python -m fedrec_tpu.cli.obs replay "$OUT/nan" > "$OUT/replay.log" 2>&1 \
    || { echo "replay did not reproduce the non-finite step"; \
         tail -20 "$OUT/replay.log"; exit 1; }
grep -q "REPRODUCED" "$OUT/replay.log" \
    || { echo "replay verdict missing"; tail -5 "$OUT/replay.log"; exit 1; }
echo "  forced-NaN: abort + complete flightrec dump + replay REPRODUCED"

echo "== [5/7] model-quality smoke (scripts/quality_smoke.sh) =="
QUALITY_SMOKE_DIR="$OUT/quality" bash scripts/quality_smoke.sh

echo "== [6/7] perf telemetry + perf-regression gate =="
# the training run of leg 1 carried obs.perf.enabled + capture_rounds=1:
# the report must render a Perf section, the perf verb must exit 0, and
# the capture window's jax.profiler trace must have landed in obs.dir
# with a pointer record in metrics.jsonl
# (report to a file, then grep: `| grep -q` would close the pipe early
# and kill the renderer with SIGPIPE under pipefail)
python -m fedrec_tpu.cli.obs report "$OUT/train" > "$OUT/report_perf.txt"
grep -q "^## Perf" "$OUT/report_perf.txt" \
    || { echo "no Perf section in the run report"; exit 1; }
run python -m fedrec_tpu.cli.obs perf "$OUT/train" > "$OUT/perf.log" \
    || { echo "fedrec-obs perf failed"; tail -20 "$OUT/perf.log"; exit 1; }
grep -q "Roofline verdicts" "$OUT/perf.log" \
    || { echo "perf verb missing the roofline table"; exit 1; }
ls -d "$OUT"/train/perf_capture_r* > /dev/null 2>&1 \
    || { echo "no capture-window trace under $OUT/train"; exit 1; }
grep -q '"kind": "perf_capture"' "$OUT/train/metrics.jsonl" \
    || { echo "no perf_capture pointer record in metrics.jsonl"; exit 1; }
echo "  perf: report section + verb + capture window + pointer record ok"

# the gate: bank a fresh seeded baseline, pass a clean re-check, then
# prove the forced-regression mode exits nonzero NAMING the lane
run python benchmarks/perf_gate.py --bank --out "$OUT/perf_gate.json" \
    > "$OUT/perf_gate.log" 2>&1 \
    || { tail -20 "$OUT/perf_gate.log"; exit 1; }
run python benchmarks/perf_gate.py --check --out "$OUT/perf_gate.json" \
    >> "$OUT/perf_gate.log" 2>&1 \
    || { echo "clean perf-gate check failed"; tail -20 "$OUT/perf_gate.log"; exit 1; }
if run python benchmarks/perf_gate.py --check --out "$OUT/perf_gate.json" \
    --demo-regression steps_per_sec >> "$OUT/perf_gate.log" 2>&1; then
    echo "forced perf regression did NOT fail the gate"; exit 1
fi
grep -q "REGRESSION lane steps_per_sec" "$OUT/perf_gate.log" \
    || { echo "gate failure did not name the lane"; tail -5 "$OUT/perf_gate.log"; exit 1; }
echo "  perf gate: banked + clean pass + forced regression names the lane"

echo "== [7/7] continuous-watch smoke (scripts/watch_smoke.sh) =="
WATCH_SMOKE_DIR="$OUT/watch" bash scripts/watch_smoke.sh
echo "OBS_SMOKE=PASS"
